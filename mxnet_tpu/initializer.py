"""Weight initializers.

TPU-native counterpart of the reference's ``python/mxnet/initializer.py``
(286 lines): name-pattern dispatch (``_weight``/``_bias``/``_gamma``/...),
Uniform/Normal/Orthogonal/Xavier/MSRAPrelu, Load/Mixed wrappers.  Random
draws use jax.random with a per-call split of the global framework key
(mxnet_tpu.random) so runs are reproducible under mx.random.seed().
"""
from __future__ import annotations

import logging
import re

import numpy as _np
import jax
import jax.numpy as jnp

from .base import MXNetError
from .ndarray import NDArray
from . import random as _random

__all__ = ["Initializer", "Uniform", "Normal", "Orthogonal", "Xavier",
           "MSRAPrelu", "Constant", "One", "Zero", "Bilinear", "Load", "Mixed"]


class Initializer(object):
    """Base: dispatch on parameter name (role: initializer.py:15 __call__).

    The parameter's name suffix selects the handler; the first matching
    suffix in ``_SUFFIX_RULES`` wins (``moving_inv_var`` must be listed
    before ``moving_var`` would ever match it, hence ordered rules rather
    than a dict).
    """

    _SUFFIX_RULES = (
        ("bias", "_init_bias"),
        ("gamma", "_init_gamma"),
        ("beta", "_init_beta"),
        ("weight", "_init_weight"),
        ("moving_mean", "_init_zero"),
        ("moving_inv_var", "_init_zero"),
        ("moving_var", "_init_one"),
        ("moving_avg", "_init_zero"),
    )

    def __call__(self, name, arr):
        if not isinstance(name, str):
            raise TypeError("name must be a string")
        if not isinstance(arr, NDArray):
            raise TypeError("arr must be NDArray")
        if name.startswith("upsampling"):
            self._init_bilinear(name, arr)
            return
        for suffix, handler in self._SUFFIX_RULES:
            if name.endswith(suffix):
                getattr(self, handler)(name, arr)
                return
        self._init_default(name, arr)

    def _init_bilinear(self, _, arr):
        shape = arr.shape
        weight = _np.zeros(int(_np.prod(shape)), dtype="float32")
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(_np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr._set_data(jnp.asarray(weight.reshape(shape)))

    def _init_zero(self, _, arr):
        arr._set_data(jnp.zeros(arr.shape, dtype=arr.dtype))

    def _init_one(self, _, arr):
        arr._set_data(jnp.ones(arr.shape, dtype=arr.dtype))

    def _init_bias(self, _, arr):
        self._init_zero(_, arr)

    def _init_gamma(self, _, arr):
        self._init_one(_, arr)

    def _init_beta(self, _, arr):
        self._init_zero(_, arr)

    def _init_weight(self, name, arr):
        raise NotImplementedError("Must override _init_weight")

    def _init_default(self, name, _):
        raise ValueError(
            "Unknown initialization pattern for %s. Default initialization "
            "is now limited to \"weight\", \"bias\", \"gamma\" (1.0), and "
            "\"beta\" (0.0). Please use mx.sym.Variable(init=mx.init.*) to "
            "set the initialization pattern" % name)


class Load(object):
    """Init from existing param dict, fall back to ``default_init``
    (parity: initializer.py Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            from .ndarray import load as nd_load
            param = nd_load(param)
        self.param = {}
        for name, arr in param.items():
            if name.startswith("arg:") or name.startswith("aux:"):
                self.param[name[4:]] = arr
            else:
                self.param[name] = arr
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            if arr.shape != self.param[name].shape:
                raise MXNetError(
                    "Parameter %s cannot be initialized from loading. "
                    "Shape mismatch, target %s vs loaded %s"
                    % (name, arr.shape, self.param[name].shape))
            arr._set_data(self.param[name].data)
            if self.verbose:
                logging.info("Initialized %s by loading", name)
        else:
            if self.default_init is None:
                raise MXNetError(
                    "Cannot Initialize parameter %s. Not found in loaded "
                    "param and no default initializer provided" % name)
            self.default_init(name, arr)
            if self.verbose:
                logging.info("Initialized %s by default", name)


class Mixed(object):
    """Regex-pattern-dispatched initializer list (parity: initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        assert len(patterns) == len(initializers)
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise ValueError(
            "Parameter name %s did not match any pattern. Consider adding a "
            "\".*\" pattern at the end with default Initializer." % name)


class Constant(Initializer):
    """Fill with a constant regardless of the name pattern."""

    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, name, arr):
        arr._set_data(jnp.full(arr.shape, self.value, dtype=arr.dtype))


class Zero(Constant):
    def __init__(self):
        super().__init__(0.0)


class One(Constant):
    def __init__(self):
        super().__init__(1.0)


class Uniform(Initializer):
    """U(-scale, scale) (parity: initializer.py Uniform)."""

    def __init__(self, scale=0.07):
        self.scale = scale

    def _init_weight(self, _, arr):
        key = _random.next_key()
        arr._set_data(jax.random.uniform(
            key, arr.shape, dtype=jnp.float32,
            minval=-self.scale, maxval=self.scale).astype(arr.dtype))


class Normal(Initializer):
    """N(0, sigma) (parity: initializer.py Normal)."""

    def __init__(self, sigma=0.01):
        self.sigma = sigma

    def _init_weight(self, _, arr):
        key = _random.next_key()
        arr._set_data((jax.random.normal(key, arr.shape, dtype=jnp.float32)
                       * self.sigma).astype(arr.dtype))


class Orthogonal(Initializer):
    """(Scaled) orthogonal matrix via QR/SVD (parity: initializer.py Orthogonal)."""

    def __init__(self, scale=1.414, rand_type="uniform"):
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:]))
        key = _random.next_key()
        if self.rand_type == "uniform":
            tmp = jax.random.uniform(key, (nout, nin), minval=-1.0, maxval=1.0)
        else:
            tmp = jax.random.normal(key, (nout, nin))
        u, _s, v = _np.linalg.svd(_np.asarray(tmp), full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        arr._set_data(jnp.asarray(self.scale * q.reshape(arr.shape),
                                  dtype=arr.dtype))


class Xavier(Initializer):
    """Xavier/Glorot (parity: initializer.py Xavier): factor from fan_in/out,
    rnd_type uniform or gaussian."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    _FACTORS = {"avg": lambda fi, fo: (fi + fo) / 2.0,
                "in": lambda fi, fo: fi,
                "out": lambda fi, fo: fo}

    def _init_weight(self, name, arr):
        shape = arr.shape
        receptive = _np.prod(shape[2:]) if len(shape) > 2 else 1.0
        fan_in = shape[1] * receptive
        fan_out = shape[0] * receptive
        if self.factor_type not in self._FACTORS:
            raise ValueError("Xavier factor_type must be one of %s, got %r"
                             % (sorted(self._FACTORS), self.factor_type))
        factor = self._FACTORS[self.factor_type](fan_in, fan_out)
        scale = _np.sqrt(self.magnitude / factor)
        key = _random.next_key()
        if self.rnd_type == "uniform":
            val = jax.random.uniform(key, shape, minval=-scale, maxval=scale)
        elif self.rnd_type == "gaussian":
            val = jax.random.normal(key, shape) * scale
        else:
            raise ValueError("Xavier rnd_type must be uniform or gaussian, "
                             "got %r" % (self.rnd_type,))
        arr._set_data(val.astype(arr.dtype))


class MSRAPrelu(Xavier):
    """He init adjusted for PReLU slope (parity: initializer.py MSRAPrelu)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)


class Bilinear(Initializer):
    """Bilinear upsampling kernel for deconvolution weights."""

    def _init_weight(self, name, arr):
        self._init_bilinear(name, arr)
