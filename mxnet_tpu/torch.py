"""Torch-backed imperative NDArray functions.

Parity: python/mxnet/torch.py of the reference, which exposed
Torch tensor math on NDArrays (``import mxnet.torch as th;
th.add(a, b)``), executed by a Lua Torch backend behind
``MXFuncInvokeEx``.  Here the backend is PyTorch on host: any
``torch.<fn>`` usable on tensors is resolved lazily by name, applied to
the NDArray inputs, and the result wrapped back — an interop
convenience, NOT a device path (torch never reaches the TPU; use the
registered ops for compiled compute).

    import mxnet_tpu.torch as th
    c = th.add(a, b)          # a, b: mx.nd.NDArray
    m = th.mm(a, b)
    th.exp(a, out=c)          # reference-style output buffer
"""
from __future__ import annotations

import numpy as _np

from .base import MXNetError
from .ndarray import NDArray

_torch = None


def _backend():
    global _torch
    if _torch is None:
        try:
            import torch as _t
        except ImportError as exc:        # pragma: no cover
            raise MXNetError("mxnet_tpu.torch needs the 'torch' package "
                             "installed") from exc
        _torch = _t
    return _torch


def _to_torch(value):
    if isinstance(value, NDArray):
        return _backend().from_numpy(_np.ascontiguousarray(value.asnumpy()))
    return value


def _apply(fn_name, *args, out=None, **kwargs):
    torch = _backend()
    fn = getattr(torch, fn_name, None)
    if fn is None:
        raise MXNetError("torch has no function %r" % fn_name)
    res = fn(*[_to_torch(a) for a in args],
             **{k: _to_torch(v) for k, v in kwargs.items()})
    if isinstance(res, tuple):
        res = res[0]
    host = res.detach().cpu().numpy()
    if out is not None:
        out._set_data(host)
        return out
    return NDArray(host)


def __getattr__(name):
    """Resolve ``th.<name>`` lazily against the torch namespace (the
    reference enumerated its TH registry at import; torch's surface is
    the registry here)."""
    if name.startswith("_"):
        raise AttributeError(name)
    torch = _backend()
    if not callable(getattr(torch, name, None)):
        raise AttributeError("torch has no function %r" % name)

    def wrapped(*args, out=None, **kwargs):
        return _apply(name, *args, out=out, **kwargs)

    wrapped.__name__ = name
    wrapped.__doc__ = (getattr(torch, name).__doc__ or
                       "torch.%s on NDArrays" % name)
    return wrapped
