"""URI-aware streams: the dmlc::Stream role (SURVEY §2.11).

In the reference every file path is a ``dmlc::Stream`` URI, which is what
makes data and checkpoints cloud-capable (docs/how_to/cloud.md:84 trains
straight off S3).  Here any plain path keeps using builtin ``open``;
paths carrying a scheme (``s3://``, ``gs://``, ``hdfs://``, ``memory://``,
...) route through fsspec.  Two entry points:

- :func:`open_uri` — file-like handle for streaming read/write.
- :func:`local_path` — a REAL local filesystem path for consumers that
  need one (the native RecordIO reader, mmap users): remote objects are
  spooled to a temp file on read and uploaded on close for write.

``file://`` is normalized to a plain local path.
"""
from __future__ import annotations

import contextlib
import os
import re
import shutil
import tempfile

__all__ = ["has_scheme", "open_uri", "local_path"]

_SCHEME_RE = re.compile(r"^[a-zA-Z][a-zA-Z0-9+.\-]*://")


def _strip_file(uri):
    return uri[len("file://"):] if uri.startswith("file://") else uri


def has_scheme(uri):
    """True when ``uri`` names a remote object (scheme other than file)."""
    uri = str(uri)
    return bool(_SCHEME_RE.match(uri)) and not uri.startswith("file://")


def _fs_open(uri, mode):
    import fsspec
    return fsspec.open(uri, mode).open()


def open_uri(uri, mode="rb"):
    """Open ``uri`` for streaming; local paths use builtin open."""
    uri = _strip_file(str(uri))
    if not has_scheme(uri):
        return open(uri, mode)
    return _fs_open(uri, mode)


@contextlib.contextmanager
def local_path(uri, mode="r"):
    """Yield a local filesystem path standing in for ``uri``.

    mode "r": remote objects are downloaded to a spool file (deleted on
    exit).  mode "w": a spool file is yielded and uploaded to ``uri`` on
    clean exit.  Local paths are yielded unchanged either way.
    """
    uri = _strip_file(str(uri))
    if not has_scheme(uri):
        yield uri
        return
    suffix = os.path.splitext(uri)[1]
    fd, tmp = tempfile.mkstemp(suffix=suffix)
    os.close(fd)
    try:
        if mode == "r":
            with _fs_open(uri, "rb") as src, open(tmp, "wb") as dst:
                shutil.copyfileobj(src, dst)
            yield tmp
        elif mode == "w":
            yield tmp
            with open(tmp, "rb") as src, _fs_open(uri, "wb") as dst:
                shutil.copyfileobj(src, dst)
        else:
            raise ValueError("local_path mode must be 'r' or 'w', got %r"
                             % mode)
    finally:
        os.unlink(tmp)
