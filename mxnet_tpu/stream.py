"""URI-aware streams: the dmlc::Stream role (SURVEY §2.11).

In the reference every file path is a ``dmlc::Stream`` URI, which is what
makes data and checkpoints cloud-capable (docs/how_to/cloud.md:84 trains
straight off S3).  Here any plain path keeps using builtin ``open``;
paths carrying a scheme (``s3://``, ``gs://``, ``hdfs://``, ``memory://``,
...) route through fsspec via :func:`open_uri`.  Consumers that need a
real local fd (the native RecordIO reader, ImageRecordIter's chunked
scan) spool remote objects through a temp file themselves — their spool
lifetimes outlive any ``with`` block (spools survive ``reset()`` and
upload on ``close()``), so no context-manager helper is offered here.

``file://`` is normalized to a plain local path.
"""
from __future__ import annotations

import re

__all__ = ["has_scheme", "open_uri"]

_SCHEME_RE = re.compile(r"^[a-zA-Z][a-zA-Z0-9+.\-]*://")


def _strip_file(uri):
    return uri[len("file://"):] if uri.startswith("file://") else uri


def has_scheme(uri):
    """True when ``uri`` names a remote object (scheme other than file)."""
    uri = str(uri)
    return bool(_SCHEME_RE.match(uri)) and not uri.startswith("file://")


def _fs_open(uri, mode):
    import fsspec
    return fsspec.open(uri, mode).open()


def open_uri(uri, mode="rb"):
    """Open ``uri`` for streaming; local paths use builtin open."""
    uri = _strip_file(str(uri))
    if not has_scheme(uri):
        return open(uri, mode)
    return _fs_open(uri, mode)
