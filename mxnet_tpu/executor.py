"""Executor: bind a Symbol to devices and run it as ONE XLA computation.

This is the TPU-native replacement of the reference's GraphExecutor
(``src/symbol/graph_executor.cc``, ``Executor::Bind`` at :1151) — SURVEY §3.2:
the whole Init pipeline (backward pass construction, context assignment,
memory planning, op instantiation, bulk segments) collapses into tracing the
graph into a jax function and letting XLA compile/fuse/plan it:

- ``MakeBackwardPass`` (static_graph.cc:395)  -> jax.vjp over the traced fwd
- grad_req write/add/null (OpReqType)         -> post-vjp combine
- memory plan + GraphStoragePool              -> XLA buffer planning/donation
- bulk segments / cached engine ops           -> a single jitted computation
- per-shape rebinding (Executor.reshape)      -> jit's shape-keyed compile cache

Monitor callbacks (graph_executor.cc:937) run via an eager interpret mode.
"""
from __future__ import annotations

import numpy as _np

import jax
import jax.numpy as jnp

from .base import MXNetError
from .context import Context
from .ndarray import NDArray, zeros
from . import random as _random

_ZERO_KEY = None


def _zero_key():
    global _ZERO_KEY
    if _ZERO_KEY is None:
        _ZERO_KEY = jax.random.PRNGKey(0)
    return _ZERO_KEY


__all__ = ["Executor", "simple_bind", "trace_residual_bytes"]


def trace_residual_bytes(trace, arg_values, aux_values, wrt_names):
    """Bytes of residuals jax's vjp would save across ``trace`` when
    differentiating wrt ``wrt_names`` — the backend-independent
    activation-memory number (what mirroring shrinks).  Shared by
    Executor.backward_residual_bytes, the multichip dryrun, and the
    mirror tests.  Returns None when the saved-residuals introspection
    (a private jax API) is unavailable."""
    try:
        from jax._src.ad_checkpoint import saved_residuals
    except ImportError:
        return None
    wrt = {n: arg_values[n] for n in wrt_names}

    def f(wrt_values):
        merged = dict(arg_values)
        merged.update(wrt_values)
        return trace(merged, aux_values, _zero_key(), True)

    total = 0
    for aval, _desc in saved_residuals(f, wrt):
        size = getattr(aval, "size", None)
        dtype = getattr(aval, "dtype", None)
        if size is not None and dtype is not None:
            total += int(size) * dtype.itemsize
    return total


def _as_list(obj, names, what):
    """Normalize list-or-dict user input to a list aligned with ``names``."""
    if obj is None:
        return [None] * len(names)
    if isinstance(obj, dict):
        return [obj.get(n) for n in names]
    obj = list(obj)
    if len(obj) != len(names):
        raise MXNetError("%s: expected %d entries (%s), got %d"
                         % (what, len(names), names, len(obj)))
    return obj




class _Program:
    """Compiled form of a symbol graph: pure trace + jitted entries."""

    __slots__ = ("trace", "jit_forward", "jit_fwd_bwd", "needs_rng",
                 "_jit_forward_mon", "monitor_sink")

    def __init__(self, trace, jit_forward, jit_fwd_bwd, needs_rng):
        self.trace = trace
        self.jit_forward = jit_forward
        self.jit_fwd_bwd = jit_fwd_bwd
        self.needs_rng = needs_rng
        self._jit_forward_mon = None
        self.monitor_sink = None

    def jit_forward_monitored(self):
        """Compiled forward that streams every op output to the installed
        monitor through ``jax.debug.callback`` — per-op stats come from the
        SAME XLA computation that training runs, not an eager re-trace
        (parity: graph_executor.cc:937-951 fires inside the real executor).
        The sink is read through ``self`` at call time so one compiled
        program serves every executor bound to this symbol."""
        if self._jit_forward_mon is None:
            import functools

            def dispatch(name, value):
                sink = self.monitor_sink
                if sink is not None:
                    sink(name, value)

            def monitored(arg_values, aux_values, rng, is_train):
                def mon(name, o):
                    jax.debug.callback(functools.partial(dispatch, name), o)
                return self.trace(arg_values, aux_values, rng, is_train,
                                  monitor=mon)

            self._jit_forward_mon = jax.jit(monitored,
                                            static_argnames=("is_train",))
        return self._jit_forward_mon


def _mirror_segments(op_nodes):
    """Partition the op schedule into checkpoint segments — the
    jax-native MakeBackwardPass mirror map (static_graph.cc:396-440).

    A node recomputes in backward ("is mirrored") under the reference's
    need_mirror rules (static_graph.cc:409-425): its ``force_mirroring``
    attr, or MXNET_BACKWARD_DO_MIRROR=1 for every op type outside the
    reference's skip list (heavy MXU ops whose recompute costs more than
    the activation is worth), except every MXNET_BACKWARD_MIRROR_STEP-th
    eligible node (a periodic keep so recompute chains stay bounded;
    <=0 means no periodic keep).  Consecutive mirrored nodes form ONE
    ``jax.checkpoint`` segment — internals dropped from the residual set
    and recomputed in backward — split at differing ``mirror_stage``
    attrs so users can pin stage boundaries.  ``op_nodes`` excludes
    variables (hoisted to a prelude: a weight/bias variable must not
    break an otherwise-contiguous mirror run).  Returns
    [(is_mirror, [nodes])].
    """
    import os as _os
    do_mirror = int(_os.environ.get("MXNET_BACKWARD_DO_MIRROR", "0") or 0)
    mirror_step = int(_os.environ.get("MXNET_BACKWARD_MIRROR_STEP",
                                      "100") or 100)
    if mirror_step <= 0:
        mirror_step = 1 << 62   # never hit the periodic keep
    counter = [0]
    env_skip = {"Convolution", "FullyConnected", "Concat", "SoftmaxOutput",
                "CuDNNBatchNorm"}

    def need(node):
        t = type(node.op).op_name or type(node.op).__name__
        if t == "Dropout":
            return False
        if str(node.attrs.get("force_mirroring", "")).lower() in ("true",
                                                                  "1"):
            return True
        if not do_mirror:
            return False
        if t in env_skip:
            return False
        counter[0] += 1
        if counter[0] % mirror_step == 0:
            return False
        return True

    segments = []
    for node in op_nodes:
        m = need(node)
        stage = node.attrs.get("mirror_stage") if m else None
        if segments and segments[-1][0] == m and segments[-1][2] == stage:
            segments[-1][1].append(node)
        else:
            segments.append([m, [node], stage])
    return [(m, nodes) for m, nodes, _stage in segments]


# Cross-symbol program registry (docs/perf.md "Overlap", compile cache):
# the per-symbol _jit_cache only helps when the SAME Symbol object is
# rebound, but common flows (module rebind after a bucketing change,
# Executor.reshape, rebuilding the net from the same script) produce a
# *fresh* Symbol with an identical graph.  Keying on the graph JSON hash
# lets those reuse the traced program instead of re-tracing + re-jitting.
_PROGRAM_REGISTRY = {}


def program_registry_stats():
    """Compile-cache counters ({"hits", "misses", "lowerings"}) plus
    this registry's entry count — the observable contract the serving
    warmup and the Predictor reuse tests assert on ("zero lowerings
    after warmup" is a delta of these numbers)."""
    from .parallel import overlap as _overlap
    stats = _overlap.compile_cache_stats()
    stats["programs"] = len(_PROGRAM_REGISTRY)
    return stats


def _bind_env_fingerprint(validate_mode):
    """Host state a program build bakes in beyond (symbol, group2ctx):
    the compute dtype, the backward-mirror envs read by
    ``_mirror_segments``, and the active validation-rules fingerprint.
    Folded into both the per-symbol ``_jit_cache`` key and (via
    ``ctx_key``) the global ``_PROGRAM_REGISTRY`` key so a flag flip
    between binds lowers a fresh program instead of reusing a stale one
    (MXL-X002: every baked ingredient must be a key ingredient)."""
    import os
    if validate_mode == "off":
        rules = ("off",)
    else:
        from .analysis import RULE_REGISTRY
        rules = (validate_mode,) + tuple(sorted(RULE_REGISTRY))
    return (os.environ.get("MXNET_COMPUTE_DTYPE", ""),
            os.environ.get("MXNET_BACKWARD_DO_MIRROR", ""),
            os.environ.get("MXNET_BACKWARD_MIRROR_STEP", ""),
            rules)


def _lookup_program(symbol, ctx_key, group2ctx):
    import os
    from .parallel import overlap as _overlap
    try:
        gkey = (_overlap.graph_fingerprint(symbol), ctx_key,
                os.environ.get("MXNET_COMPUTE_DTYPE", ""))
    except Exception:
        _overlap.note_lowering()
        return _build_program(symbol, group2ctx)
    prog = _PROGRAM_REGISTRY.get(gkey)
    if prog is None:
        _overlap.note_lowering()
        prog = _PROGRAM_REGISTRY[gkey] = _build_program(symbol, group2ctx)
    else:
        _overlap.note_hit()
    return prog


def _build_program(symbol, group2ctx):
    """Flatten the symbol into an executable schedule and jit it.

    Parity: the GraphExecutor Init pipeline (graph_executor.h:40-72); device
    placement for ctx_group nodes is resolved here (AssignContext analog,
    graph_executor.cc:391) with XLA inserting the transfers.  Mirrored
    nodes (static_graph.cc:396 MakeBackwardPass) lower to per-segment
    ``jax.checkpoint``: their activations leave the residual set and are
    recomputed during the vjp — the TPU-native memory/FLOPs trade.
    """
    topo = symbol._topo()
    heads = list(symbol._heads)
    n_rng = sum(1 for n in topo if not n.is_variable and n.op.need_rng)
    needs_rng = n_rng > 0
    n_rng = max(n_rng, 1)

    node_device = {}
    for node in topo:
        group = node.attrs.get("ctx_group")
        if group and group in group2ctx:
            try:
                node_device[id(node)] = group2ctx[group].jax_device
            except Exception:
                pass

    variables = [n for n in topo if n.is_variable]
    segments = _mirror_segments([n for n in topo if not n.is_variable])
    any_mirror = any(m for m, _ in segments)
    # (id(node), out_idx) values needed beyond each mirror segment: by
    # external consumers or as graph heads — everything else is internal
    # to its segment and free to drop+recompute.  Variables live in no
    # segment (prelude; seg -2) so they are always segment inputs.
    seg_of = {}
    for si, (m, nodes) in enumerate(segments):
        for n in nodes:
            seg_of[id(n)] = si
    ext_needed = {i: [] for i in range(len(segments))}
    if any_mirror:
        seen = set()

        def _mark(key, consumer_seg):
            psi = seg_of.get(key[0], -2)
            if psi >= 0 and psi != consumer_seg and key not in seen:
                seen.add(key)
                ext_needed[psi].append(key)

        for node in topo:
            if node.is_variable:
                continue
            for c, ci in node.inputs:
                _mark((id(c), ci), seg_of[id(node)])
        for n, i in heads:
            _mark((id(n), i), -1)

    def _run_node(node, values, aux_values, aux_out, key, is_train,
                  monitor):
        op = node.op
        ins = [values[(id(c), ci)] for c, ci in node.inputs]
        aux_names = ["%s_%s" % (node.name, a)
                     for a in op.list_auxiliary_states()]
        aux_in = [aux_values[a] for a in aux_names]
        outs, aux_updates = op.forward(ins, aux_in, is_train, key)
        dev = node_device.get(id(node))
        if dev is not None:
            outs = [jax.device_put(o, dev) for o in outs]
        for i, o in enumerate(outs):
            values[(id(node), i)] = o
        if aux_updates is not None:
            for a, u in zip(aux_names, aux_updates):
                aux_out[a] = u
        if monitor is not None:
            for oname, o in zip(op.list_outputs(), outs):
                monitor("%s_%s" % (node.name, oname), o)

    def _seg_aux_names(nodes):
        names = []
        for node in nodes:
            names.extend("%s_%s" % (node.name, a)
                         for a in node.op.list_auxiliary_states())
        return names

    def trace(arg_values, aux_values, rng, is_train, monitor=None):
        """Evaluate the graph; pure & jax-traceable (the 'StaticGraph run')."""
        values = {}
        aux_out = dict(aux_values)
        rngs = jax.random.split(rng, n_rng) if needs_rng else None
        rng_i = 0
        # a monitor observes every op output: that pins all activations
        # live anyway AND a checkpointed callback would double-fire on
        # recompute — monitored traces run unmirrored
        mirror_active = any_mirror and monitor is None
        for node in variables:
            values[(id(node), 0)] = arg_values[node.name]
        for si, (is_mirror, nodes) in enumerate(segments):
            seg_n_rng = sum(1 for n in nodes if n.op.need_rng)
            if not (is_mirror and mirror_active):
                for node in nodes:
                    key = None
                    if node.op.need_rng:
                        key = rngs[rng_i]
                        rng_i += 1
                    _run_node(node, values, aux_values, aux_out, key,
                              is_train, monitor)
                continue

            ext_keys = sorted(
                {(id(c), ci) for n in nodes for c, ci in n.inputs
                 if seg_of.get(id(c), -2) != si})
            out_keys = ext_needed[si]
            aux_names = _seg_aux_names(nodes)
            seg_keys = (rngs[rng_i:rng_i + seg_n_rng]
                        if needs_rng else None)
            rng_i += seg_n_rng

            def seg_fn(ext_vals, aux_in, keys, _nodes=nodes,
                       _ext_keys=ext_keys, _out_keys=out_keys):
                local = dict(zip(_ext_keys, ext_vals))
                local_aux_out = {}
                ki = 0
                for node in _nodes:
                    key = None
                    if node.op.need_rng:
                        key = keys[ki]
                        ki += 1
                    _run_node(node, local, aux_in, local_aux_out, key,
                              is_train, None)
                return [local[k] for k in _out_keys], local_aux_out

            seg_aux_in = {a: aux_values[a] for a in aux_names}
            seg_outs, seg_aux_out = jax.checkpoint(seg_fn)(
                [values[k] for k in ext_keys], seg_aux_in, seg_keys)
            for k, v in zip(out_keys, seg_outs):
                values[k] = v
            aux_out.update(seg_aux_out)
        outputs = [values[(id(n), i)] for n, i in heads]
        return outputs, aux_out

    def fwd_bwd(arg_values, aux_values, rng, out_grads, wrt):
        """Forward + vjp in ONE XLA computation (replaces the reference's
        explicit Backward nodes, static_graph.cc:395)."""
        def f(wrt_values):
            merged = dict(arg_values)
            merged.update(wrt_values)
            return trace(merged, aux_values, rng, True)

        (outs, aux_out), vjp_fn = jax.vjp(f, wrt)
        if out_grads is None:  # implicit loss-layer heads: cotangent of ones
            out_grads = [jnp.ones_like(o) for o in outs]
        grads = vjp_fn((out_grads,
                        jax.tree_util.tree_map(jnp.zeros_like, aux_out)))[0]
        return outs, aux_out, grads

    return _Program(trace, jax.jit(trace, static_argnames=("is_train",)),
                    jax.jit(fwd_bwd), needs_rng)

class Executor:
    """Parity: include/mxnet/symbolic.h:323 + python/mxnet/executor.py."""

    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None, group2ctx=None, shared_exec=None,
                 validate=None):
        self._symbol = symbol
        self._ctx = ctx if isinstance(ctx, Context) else Context(ctx)
        self._group2ctx = group2ctx or {}
        self._monitor_callback = None

        # bind-time graph validation knob: "warn" (default) surfaces lint
        # findings as GraphLintWarning, "error" refuses to bind a graph
        # with error-severity findings (the reference GraphExecutor's
        # fail-at-bind contract), "off" skips the pass entirely.
        # MXTPU_BIND_VALIDATE overrides the default for whole runs.
        import os as _os
        if validate is None:
            validate = _os.environ.get("MXTPU_BIND_VALIDATE", "warn")
        if validate not in ("warn", "error", "off"):
            raise MXNetError("validate must be 'warn', 'error' or 'off', "
                             "got %r" % (validate,))
        self._validate_mode = validate

        self._arg_names = symbol.list_arguments()
        self._out_names = symbol.list_outputs()
        self._aux_names = symbol.list_auxiliary_states()
        # graphs embedding host-callback ops (CustomOp/NativeOp, the
        # torch/plugin bridges) need a sync point after backward: the
        # callback replay runs on jax's async callback thread while the
        # caller may mutate host state (a torch optimizer stepping the
        # module's params in-place) as soon as backward() returns
        self._has_host_ops = any(
            getattr(node.op, "host_callback", False)
            for node in symbol._topo() if node.op is not None)

        arg_list = _as_list(args, self._arg_names, "args")
        if any(a is None for a in arg_list):
            missing = [n for n, a in zip(self._arg_names, arg_list) if a is None]
            raise MXNetError("bind: missing arguments %s" % missing)
        self.arg_arrays = arg_list
        self.arg_dict = dict(zip(self._arg_names, arg_list))

        self.grad_arrays = _as_list(args_grad, self._arg_names, "args_grad")
        self.grad_dict = {n: g for n, g in zip(self._arg_names, self.grad_arrays)
                          if g is not None}

        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in self._arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(self._arg_names, grad_req))
        else:
            self._grad_req = {n: grad_req.get(n, "null") for n in self._arg_names}
        for n in self._arg_names:
            if self._grad_req.get(n, "null") not in ("null", "write", "add"):
                raise MXNetError("invalid grad_req %r" % self._grad_req[n])
            if self._grad_req[n] != "null" and self.grad_dict.get(n) is None:
                self._grad_req[n] = "null"

        aux_list = _as_list(aux_states, self._aux_names, "aux_states")
        if any(a is None for a in aux_list):
            # allocate missing aux from inferred shapes
            shapes = {n: a.shape for n, a in self.arg_dict.items()}
            _, _, aux_shapes = symbol.infer_shape(**shapes)
            if aux_shapes is None:
                raise MXNetError("bind: cannot infer aux shapes")
            aux_list = [a if a is not None else zeros(s, ctx=self._ctx)
                        for a, s in zip(aux_list, aux_shapes)]
        self.aux_arrays = aux_list
        self.aux_dict = dict(zip(self._aux_names, aux_list))

        # static graph lint BEFORE tracing: a bad graph fails here with
        # positioned findings instead of an opaque XLA trace error
        # (GraphExecutor bind-time inference parity; analysis/).
        self.bind_issues = []
        if validate != "off":
            self._validate_bind(args, args_grad, grad_req, aux_states)

        # outputs are allocated AT BIND and updated in place by forward:
        # a handle taken once (MXExecutorOutputs, reference c_api.cc
        # MXExecutorOutputs contract) stays aliased to the executor's
        # live outputs across forwards
        out_shapes = None
        try:
            _, out_shapes, _ = symbol.infer_shape(
                **{n: a.shape for n, a in self.arg_dict.items()})
        except Exception:
            pass
        if out_shapes is not None:
            self.outputs = [NDArray(jnp.zeros(s), ctx=self._ctx)
                            for s in out_shapes]
        else:
            self.outputs = [None] * len(self._out_names)

        # The traced program is a pure function of (symbol, group2ctx,
        # baked host flags) — NOT of this executor — and is cached on the
        # symbol so every executor bound to the same graph shares one
        # compile cache (the analog of GraphStoragePool sharing; also what
        # makes repeated bind cheap).  The key folds in every env/flag the
        # build actually bakes (compute dtype, the backward-mirror envs
        # read by _mirror_segments) plus the validation-rules fingerprint,
        # so a flag flip between binds cannot reuse a stale program.
        # Caching bound methods here would pin the first executor's buffers.
        cache_key = (tuple(sorted((k, str(v))
                                  for k, v in self._group2ctx.items())),
                     _bind_env_fingerprint(self._validate_mode))
        cache = getattr(symbol, "_jit_cache", None)
        if cache is None:
            cache = symbol._jit_cache = {}
        if cache_key not in cache:
            cache[cache_key] = _lookup_program(symbol, cache_key,
                                               self._group2ctx)
        self._program = cache[cache_key]
        self._needs_rng = self._program.needs_rng
        self._jit_forward = self._program.jit_forward
        self._jit_fwd_bwd = self._program.jit_fwd_bwd
        # dispatch counters (one fused call per fit step is the contract
        # the tests assert — graph_executor.cc:842 bulk-segment analog)
        self._n_forward = 0
        self._n_fwd_bwd = 0
        self._n_fused_step = 0
        self._n_monitored_compiled = 0
        self._fused_cache = None  # (optimizer fingerprint, jitted step)

    def _validate_bind(self, args, args_grad, grad_req, aux_states):
        """Run the static analyzer with full bind context and apply the
        validate= policy: 'warn' emits one GraphLintWarning summarizing
        warning+error findings, 'error' raises MXNetError when any
        error-severity finding exists (refuse-to-bind, the reference
        GraphExecutor contract)."""
        from .analysis import analyze, format_issues, GraphLintWarning
        # no world_size= here: AnalysisContext reads
        # MXTPU_LINT_DISTRIBUTED / MXTPU_LINT_WORLD_SIZE itself, so the
        # per-rank collective-trace diff (MXL-D001..003) joins bind-time
        # validation whenever the env knob is on
        issues = analyze(
            self._symbol,
            shapes={n: tuple(a.shape) for n, a in self.arg_dict.items()},
            type_dict={n: a.dtype for n, a in self.arg_dict.items()},
            args=args, args_grad=args_grad, grad_req=grad_req,
            aux_states=aux_states, group2ctx=self._group2ctx,
            target=self._ctx.device_type)
        self.bind_issues = issues
        errors = [i for i in issues if i.severity == "error"]
        visible = [i for i in issues if i.severity != "info"]
        if errors and self._validate_mode == "error":
            raise MXNetError(
                "bind validation failed with %d error(s) (pass "
                "validate='warn'/'off' or fix the graph):\n%s"
                % (len(errors), format_issues(errors)))
        if visible:
            import warnings
            warnings.warn("graph lint found %d issue(s) at bind:\n%s"
                          % (len(visible), format_issues(visible)),
                          GraphLintWarning, stacklevel=3)

    @property
    def output_dict(self):
        """name -> output NDArray (reference executor.py output_dict);
        duplicate names raise, as the reference's _get_dict does."""
        if len(set(self._out_names)) != len(self._out_names):
            raise MXNetError("Duplicate names detected in outputs: %s"
                             % (self._out_names,))
        return dict(zip(self._out_names, self.outputs))

    def _publish_output(self, i, value):
        """Update output slot i IN PLACE: the NDArray object is stable for
        the life of the executor (MXExecutorOutputs handles stay aliased,
        reference c_api.cc MXExecutorOutputs), only its buffer moves.
        Dtype/shape may legitimately differ from the bind-time allocation
        (Cast outputs, reshape) — rebind storage directly then."""
        nd = self.outputs[i]
        if nd is None:
            self.outputs[i] = NDArray(value, ctx=self._ctx)
        elif nd.dtype == value.dtype and nd.shape == value.shape:
            nd._set_data(value)
        else:
            nd._storage = value

    @property
    def _trace(self):
        return self._program.trace

    # ------------------------------------------------------------------
    # public API (python/mxnet/executor.py parity)
    # ------------------------------------------------------------------
    def forward(self, is_train=False, **kwargs):
        for name, arr in kwargs.items():
            if name not in self.arg_dict:
                raise MXNetError("forward: unknown argument %r" % name)
            if isinstance(arr, NDArray):
                self.arg_dict[name]._set_data(arr.data)
            else:
                self.arg_dict[name]._set_data(jnp.asarray(arr))
        self._n_forward += 1
        arg_values = {n: a.data for n, a in self.arg_dict.items()}
        aux_values = {n: a.data for n, a in self.aux_dict.items()}
        rng = _random.next_key() if self._needs_rng else _zero_key()
        if self._monitor_callback is not None:
            import os as _os
            if _os.environ.get("MXTPU_MONITOR_MODE", "compiled") == "interpret":
                # eager op-by-op debugging path (NaiveEngine analog)
                outs, aux_out = self._trace(arg_values, aux_values, rng,
                                            is_train, monitor=self._run_monitor)
            else:
                prog = self._program
                prog.monitor_sink = self._run_monitor
                try:
                    outs, aux_out = prog.jit_forward_monitored()(
                        arg_values, aux_values, rng, is_train=bool(is_train))
                    # debug callbacks are asynchronous: flush them so the
                    # monitor queue is complete when toc() reads it
                    jax.effects_barrier()
                finally:
                    prog.monitor_sink = None
                self._n_monitored_compiled += 1
        else:
            outs, aux_out = self._jit_forward(arg_values, aux_values, rng,
                                              is_train=bool(is_train))
        for i, o in enumerate(outs):
            self._publish_output(i, o)
        if is_train:
            for n, a in self.aux_dict.items():
                if aux_out[n] is not aux_values[n]:
                    a._set_data(aux_out[n])
        self._last_inputs = (arg_values, aux_values, rng)
        return self.outputs

    def backward(self, out_grads=None):
        if not hasattr(self, "_last_inputs"):
            raise MXNetError("backward called before forward(is_train=True)")
        arg_values, aux_values, rng = self._last_inputs
        wrt_names = tuple(n for n in self._arg_names
                          if self._grad_req.get(n, "null") != "null")
        if not wrt_names:
            return
        if out_grads is None:
            ograds = None
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            ograds = [g.data if isinstance(g, NDArray) else jnp.asarray(g)
                      for g in out_grads]
        wrt = {n: arg_values[n] for n in wrt_names}
        self._n_fwd_bwd += 1
        _outs, _aux, grads = self._jit_fwd_bwd(arg_values, aux_values, rng,
                                               ograds, wrt)
        for n in wrt_names:
            g = grads[n]
            tgt = self.grad_dict[n]
            if self._grad_req[n] == "add":
                tgt._set_data(tgt.data + g)
            else:
                tgt._set_data(g)
        if self._has_host_ops:
            # order the host-side backward effects (torch .grad fills,
            # custom-op buffer writes) before the caller's next move
            for n in wrt_names:
                grads[n].block_until_ready()

    def forward_backward(self, out_grads=None, **kwargs):
        """Fused train step building block: one XLA computation for fwd+bwd."""
        for name, arr in kwargs.items():
            self.arg_dict[name]._set_data(
                arr.data if isinstance(arr, NDArray) else jnp.asarray(arr))
        arg_values = {n: a.data for n, a in self.arg_dict.items()}
        aux_values = {n: a.data for n, a in self.aux_dict.items()}
        rng = _random.next_key() if self._needs_rng else _zero_key()
        wrt_names = tuple(n for n in self._arg_names
                          if self._grad_req.get(n, "null") != "null")
        if out_grads is None:
            ograds = None
        else:
            ograds = [g.data if isinstance(g, NDArray) else jnp.asarray(g)
                      for g in out_grads]
        wrt = {n: arg_values[n] for n in wrt_names}
        self._n_fwd_bwd += 1
        outs, aux_out, grads = self._jit_fwd_bwd(arg_values, aux_values, rng,
                                                 ograds, wrt)
        for i, o in enumerate(outs):
            self._publish_output(i, o)
        for n, a in self.aux_dict.items():
            a._set_data(aux_out[n])
        for n in wrt_names:
            tgt = self.grad_dict[n]
            if self._grad_req[n] == "add":
                tgt._set_data(tgt.data + grads[n])
            else:
                tgt._set_data(grads[n])
        if self._has_host_ops:
            for n in wrt_names:
                grads[n].block_until_ready()
        return self.outputs

    # -- fused train step (fwd + bwd + optimizer update, ONE dispatch) --
    def _fused_compute_dtype(self):
        """Optional reduced-precision compute for the fused step
        (MXNET_COMPUTE_DTYPE=bfloat16): fwd+bwd run at MXU rate while
        master weights, optimizer state, grads and aux stay f32 — the
        policy knob the fp32-only reference never had (SURVEY §7)."""
        import os
        name = os.environ.get("MXNET_COMPUTE_DTYPE", "").strip()
        if not name or name in ("float32", "f32"):
            return None, frozenset()
        cdt = jnp.dtype(name)
        # never cast integer-valued float inputs: labels and Embedding
        # vocab ids above 256 would silently round in bf16
        exempt = {n for n in self._arg_names if n.endswith("label")}
        for node in self._symbol._topo():
            if node.op is not None and \
                    getattr(node.op, "op_name", "") == "Embedding":
                src, _ = node.inputs[0]
                if src.is_variable:
                    exempt.add(src.name)
        return cdt, frozenset(exempt)

    def _build_fused_step(self, optimizer):
        """Jit fwd+bwd+update as one XLA computation — the full analog of
        the reference's bulk segments (graph_executor.cc:842-892): the
        whole fit step is one dispatch, with the optimizer math fused in
        (≡ server-side update, kvstore_dist_server.h:164, run on-device)."""
        trace = self._program.trace
        wrt_names = tuple(n for n in self._arg_names
                          if self._grad_req.get(n, "null") != "null")
        upd = optimizer.update_fn
        pre = optimizer._preprocess_grad
        # per-param lr/wd multipliers are static floats at trace time
        # (reference _get_lr/_get_wd, optimizer.py:122-141)
        name2idx = {n: i for i, n in optimizer.idx2name.items()}
        lrm, wdm = {}, {}
        for n in wrt_names:
            idx = name2idx.get(n, n)
            lrm[n] = optimizer.lr_mult.get(
                idx, optimizer.lr_mult.get(n, 1.0))
            wdm[n] = optimizer.wd_mult.get(
                idx, optimizer.wd_mult.get(n, 1.0))

        cdt, exempt = self._fused_compute_dtype()

        def cast(name, a):
            if cdt is None or name in exempt or \
                    not jnp.issubdtype(a.dtype, jnp.floating):
                return a
            return a.astype(cdt)

        def step(arg_values, aux_values, rng, states, lr, wd, t):
            def f(wrt_values):
                # the cast is INSIDE f: vjp through astype returns f32
                # cotangents for the f32 master weights
                merged = {n: cast(n, v) for n, v in arg_values.items()}
                merged.update({n: cast(n, v)
                               for n, v in wrt_values.items()})
                aux_in = {n: cast(n, v) for n, v in aux_values.items()}
                outs, aux_out = trace(merged, aux_in, rng, True)
                if cdt is not None:     # aux (bn stats) stored f32
                    aux_out = {k: v.astype(aux_values[k].dtype)
                               for k, v in aux_out.items()}
                return outs, aux_out

            wrt = {n: arg_values[n] for n in wrt_names}
            (outs, aux_out), vjp_fn = jax.vjp(f, wrt)
            ones = [jnp.ones_like(o) for o in outs]
            grads = vjp_fn(
                (ones, jax.tree_util.tree_map(jnp.zeros_like, aux_out)))[0]
            new_w, new_s = {}, {}
            for n in wrt_names:
                g = pre(grads[n])
                w, s = upd(arg_values[n], g, states.get(n),
                           lr * lrm[n], wd * wdm[n], t)
                new_w[n] = w
                if s is not None:
                    new_s[n] = s
            return outs, aux_out, grads, new_w, new_s

        return wrt_names, jax.jit(step, donate_argnums=(3,))

    def _get_fused(self, optimizer):
        """(wrt_names, jitted step) for this optimizer, cached by a
        value fingerprint over exactly what _build_fused_step bakes:
        optimizer class, hyperparameter scalars (minus the per-step
        update counters, which mutate every step and would defeat the
        cache), the per-param multiplier maps, and the compute dtype.
        An id()-keyed cache would miss for a fresh-but-identical
        optimizer (needless relower of the whole fused step) and could
        falsely hit on a gc-recycled id (stale program, wrong
        hyperparameters) — MXL-X002."""
        import os
        from .parallel import overlap as _overlap
        hypers = {k: v for k, v in sorted(vars(optimizer).items())
                  if isinstance(v, (int, float, bool, str, type(None)))
                  and k not in ("num_update", "begin_num_update")}
        key = _overlap.cache_key(
            type(optimizer).__name__, hypers,
            getattr(optimizer, "lr_mult", None),
            getattr(optimizer, "wd_mult", None),
            getattr(optimizer, "idx2name", None),
            os.environ.get("MXNET_COMPUTE_DTYPE", ""))
        if self._fused_cache is None or self._fused_cache[0] != key:
            self._fused_cache = (key, self._build_fused_step(optimizer))
        return self._fused_cache[1]

    def fused_step(self, optimizer, states, num_update, **kwargs):
        """Run one full train step (forward + backward + optimizer update)
        as a single XLA dispatch.  Writes updated params into the bound
        arg arrays, grads into grad arrays, aux/outputs as forward does.
        ``states`` is a dict name -> optimizer-state pytree (jax arrays),
        mutated-by-replacement and returned.
        """
        wrt_names, jit_step = self._get_fused(optimizer)
        for name, arr in kwargs.items():
            self.arg_dict[name]._set_data(
                arr.data if isinstance(arr, NDArray) else jnp.asarray(arr))
        arg_values = {n: a.data for n, a in self.arg_dict.items()}
        aux_values = {n: a.data for n, a in self.aux_dict.items()}
        rng = _random.next_key() if self._needs_rng else _zero_key()
        if optimizer.lr_scheduler is not None:
            lr = optimizer.lr_scheduler(num_update)
        else:
            lr = optimizer.lr
        self._n_fused_step += 1
        outs, aux_out, grads, new_w, new_s = jit_step(
            arg_values, aux_values, rng, states,
            jnp.float32(lr), jnp.float32(optimizer.wd),
            jnp.int32(num_update))
        for i, o in enumerate(outs):
            self._publish_output(i, o)
        for n, a in self.aux_dict.items():
            a._set_data(aux_out[n])
        for n in wrt_names:
            self.grad_dict[n]._set_data(grads[n])
            self.arg_dict[n]._set_data(new_w[n])
        return new_s

    def _lower_fused(self, optimizer, states):
        _wrt_names, jit_step = self._get_fused(optimizer)
        arg_values = {n: a.data for n, a in self.arg_dict.items()}
        aux_values = {n: a.data for n, a in self.aux_dict.items()}
        return jit_step.lower(arg_values, aux_values, _zero_key(), states,
                              jnp.float32(0.01), jnp.float32(0.0),
                              jnp.int32(1))

    def lower_fused_step(self, optimizer, states):
        """Optimized-HLO text of the fused step for the currently bound
        arrays — introspection hook (tests assert the sharded step carries
        an all-reduce; the perf story's equivalent of debug_str)."""
        return self._lower_fused(optimizer, states).compile().as_text()

    def fused_step_memory_analysis(self, optimizer, states):
        """XLA's compiled memory analysis of the fused train step
        (``temp_size_in_bytes`` is the activation/workspace peak the
        mirroring trade shrinks — the MemoryCost introspection the
        reference's example/memcost reads off the allocator logs)."""
        return self._lower_fused(optimizer, states).compile(
            ).memory_analysis()

    def backward_residual_bytes(self):
        """Bytes of residuals jax saves between forward and backward for
        the bound shapes — the activation-memory quantity mirroring
        (``force_mirroring``/MXNET_BACKWARD_DO_MIRROR ->
        ``jax.checkpoint``) exists to shrink.  Backend-independent: read
        from the partial-eval trace, not the compiled executable (XLA:CPU
        does not attribute temp buffers).  Returns None when jax's
        saved-residuals introspection is unavailable."""
        arg_values = {n: a.data for n, a in self.arg_dict.items()}
        aux_values = {n: a.data for n, a in self.aux_dict.items()}
        wrt_names = tuple(n for n in self._arg_names
                          if self._grad_req.get(n, "null") != "null")
        return trace_residual_bytes(self._program.trace, arg_values,
                                    aux_values, wrt_names)

    def init_fused_states(self, optimizer):
        """Optimizer-state arrays for every learnable arg (fused path)."""
        states = {}
        for n in self._arg_names:
            if self._grad_req.get(n, "null") == "null":
                continue
            a = self.arg_dict[n]
            s = optimizer.create_state_arrays(a.shape, a.dtype)
            if s is not None:
                states[n] = s
        return states

    # -- monitor (MXExecutorSetMonitorCallback parity) ------------------
    def set_monitor_callback(self, callback):
        self._monitor_callback = callback

    def _run_monitor(self, name, value):
        self._monitor_callback(name, NDArray(value, ctx=self._ctx))

    # -- param management ----------------------------------------------
    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for name, arr in arg_params.items():
            if name in self.arg_dict:
                arr.copyto(self.arg_dict[name])
            elif not allow_extra_params:
                raise MXNetError("unknown argument %r" % name)
        if aux_params:
            for name, arr in aux_params.items():
                if name in self.aux_dict:
                    arr.copyto(self.aux_dict[name])
                elif not allow_extra_params:
                    raise MXNetError("unknown aux state %r" % name)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **new_shapes):
        """Re-bind to new input shapes (executor.py:270). Param arrays are
        shared; data/label arrays reallocated; jit recompiles per shape."""
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**new_shapes)
        if arg_shapes is None:
            raise MXNetError("reshape: cannot infer shapes from %s" % new_shapes)
        new_args = {}
        new_grads = {}
        for name, shape in zip(self._arg_names, arg_shapes):
            cur = self.arg_dict[name]
            if tuple(cur.shape) == tuple(shape):
                new_args[name] = cur
                if name in self.grad_dict:
                    new_grads[name] = self.grad_dict[name]
            else:
                if not partial_shaping and name not in new_shapes:
                    raise MXNetError(
                        "reshape changed shape of %s; pass partial_shaping=True"
                        % name)
                new_args[name] = zeros(shape, ctx=self._ctx, dtype=cur.dtype)
                if name in self.grad_dict:
                    new_grads[name] = zeros(shape, ctx=self._ctx, dtype=cur.dtype)
        aux = {n: a for n, a in self.aux_dict.items()}
        return Executor(self._symbol, self._ctx, new_args, new_grads,
                        self._grad_req, aux, group2ctx=self._group2ctx,
                        shared_exec=self, validate=self._validate_mode)

    def debug_str(self):
        """Execution plan dump (GraphExecutor::Print parity); under jit the
        real plan is XLA's — expose both our schedule and cost analysis."""
        lines = [self._symbol.debug_str(), ""]
        total = sum(_np.prod(a.shape) * a.dtype.itemsize
                    for a in self.arg_arrays + self.aux_arrays
                    + [g for g in self.grad_arrays if g is not None])
        lines.append("Total %d MB allocated (args+grads+aux)" % (total // (1 << 20)))
        return "\n".join(lines)


def simple_bind(symbol, ctx, grad_req="write", type_dict=None, group2ctx=None,
                shared_exec=None, validate=None, **kwargs):
    """Allocate arg/grad/aux arrays from inferred shapes and bind
    (parity: symbol.py:630-710)."""
    arg_shapes, _, aux_shapes = symbol.infer_shape(**kwargs)
    if arg_shapes is None:
        raise MXNetError("simple_bind: cannot infer shapes from %s" % kwargs)
    arg_names = symbol.list_arguments()
    type_dict = type_dict or {}
    args = {}
    grads = {}
    for name, shape in zip(arg_names, arg_shapes):
        dtype = type_dict.get(name, _np.float32)
        args[name] = zeros(shape, ctx=ctx, dtype=dtype)
        req = grad_req if isinstance(grad_req, str) else \
            (grad_req.get(name, "null") if isinstance(grad_req, dict)
             else dict(zip(arg_names, grad_req)).get(name, "null"))
        if req != "null":
            grads[name] = zeros(shape, ctx=ctx, dtype=dtype)
    aux = [zeros(s, ctx=ctx) for s in aux_shapes]
    return Executor(symbol, ctx, args, grads, grad_req, aux,
                    group2ctx=group2ctx, shared_exec=shared_exec,
                    validate=validate)
