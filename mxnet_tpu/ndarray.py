"""NDArray: the imperative n-d array on XLA buffers.

TPU-native reimplementation of the reference's NDArray
(``include/mxnet/ndarray.h:31-369``, ``src/ndarray/ndarray.cc``,
``python/mxnet/ndarray.py``).  Key design translation (SURVEY §7 stage 2):

- The reference pairs every array with an Engine variable and pushes each
  mutation through a threaded dependency engine (ndarray.cc:96-352).  On TPU,
  XLA's async dispatch *is* the dependency engine: every jax op returns
  immediately with a future-backed buffer and data dependencies serialize
  execution.  ``wait_to_read`` maps to ``block_until_ready``.
- In-place mutation (``+=``, ``a[1:3] = x``) has no native XLA analog; we keep
  reference *aliasing semantics* with write-through views: ``a[i]``/``slice``
  return views holding a getter/setter pair onto the parent buffer; writes
  rebind the parent's buffer via ``.at[].set()`` (donation makes this cheap
  under jit) and reads always see the parent's current buffer.
- The per-op registered-function table (``NDArrayFunctionReg``,
  include/mxnet/ndarray.h:508) becomes plain module functions; the same
  compute bodies are shared with the symbolic op registry so imperative and
  symbolic results agree (mirrors how simple-ops register into both paths,
  src/operator/operator_util.cc:87-120).
"""
from __future__ import annotations

import struct
import threading
import weakref

import numpy as _np

from .base import MXNetError, mx_real_t, dtype_np_to_mx, dtype_mx_to_np
from .context import Context, current_context

__all__ = [
    "NDArray", "zeros", "ones", "empty", "full", "array", "arange",
    "concatenate", "load", "save", "waitall", "onehot_encode", "imdecode",
]

import jax
import jax.numpy as jnp

# weak registry of this framework's arrays; waitall() blocks on these
# instead of scanning the process-wide jax heap
_LIVE = weakref.WeakSet()
# Guards _LIVE snapshot/insert: background threads (PrefetchingIter
# workers, async-checkpoint engine callbacks) create NDArrays while
# waitall iterates, and WeakSet raises on concurrent mutation.
_LIVE_LOCK = threading.Lock()


def _ctx_device(ctx):
    try:
        return ctx.jax_device
    except MXNetError:
        raise  # out-of-range device id is a real user error
    except Exception:
        return None  # backend not initialisable (e.g. no accelerator): stay on default


class NDArray:
    """An n-dimensional array whose storage lives on a JAX device.

    Parity: include/mxnet/ndarray.h:31.  Unlike the reference there is no
    explicit Chunk{Storage::Handle, Engine::Var}; the jax.Array plays both
    roles (buffer + dependency token).
    """

    __slots__ = ("_storage", "_ctx", "_writable", "_parent", "_getter",
                 "_setter", "__weakref__")

    def __init__(self, data, ctx=None, writable=True, _parent=None,
                 _getter=None, _setter=None):
        with _LIVE_LOCK:
            _LIVE.add(self)
        self._parent = _parent
        self._getter = _getter
        self._setter = _setter
        self._writable = writable
        if _parent is not None:
            self._storage = None
            self._ctx = _parent._ctx
            return
        if isinstance(data, NDArray):
            data = data.data
        if not isinstance(data, jax.Array):
            data = jnp.asarray(data)
        ctx = ctx if ctx is not None else current_context()
        dev = _ctx_device(ctx)
        if dev is not None and (not hasattr(data, "devices") or dev not in data.devices()):
            data = jax.device_put(data, dev)
        self._storage = data
        self._ctx = ctx

    # ------------------------------------------------------------------
    # storage access (views resolve through the parent lazily => aliasing)
    # ------------------------------------------------------------------
    @property
    def data(self):
        """Current jax.Array value (resolves views against the live parent)."""
        if self._parent is not None:
            return self._getter(self._parent.data)
        return self._storage

    def _set_data(self, value):
        """Rebind the underlying buffer; views write through to the parent.

        This is the moral equivalent of an engine write-dependency push
        (threaded_engine.cc:53-79): in XLA, rebinding to a new buffer whose
        computation depends on the old one gives the same serialization.
        """
        if not self._writable:
            raise MXNetError("trying to write to a read-only NDArray")
        value = jnp.asarray(value, dtype=self.dtype)
        if value.shape != self.shape:
            value = jnp.broadcast_to(value, self.shape)
        if self._parent is not None:
            self._parent._set_data(self._setter(self._parent.data, value))
        else:
            self._storage = value

    # ------------------------------------------------------------------
    # basic properties
    # ------------------------------------------------------------------
    @property
    def shape(self):
        return tuple(self.data.shape)

    @property
    def size(self):
        n = 1
        for s in self.shape:
            n *= s
        return n

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def dtype(self):
        return _np.dtype(self.data.dtype)

    @property
    def context(self) -> Context:
        return self._ctx

    @property
    def writable(self):
        return self._writable

    def __repr__(self):
        return "<NDArray %s @%s>" % ("x".join(str(s) for s in self.shape), self._ctx)

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    # ------------------------------------------------------------------
    # sync points (engine WaitToRead/WaitToWrite/WaitForAll parity,
    # include/mxnet/ndarray.h:108-124)
    # ------------------------------------------------------------------
    def wait_to_read(self):
        self.data.block_until_ready()

    def wait_to_write(self):
        self.data.block_until_ready()

    # ------------------------------------------------------------------
    # host interop
    # ------------------------------------------------------------------
    def asnumpy(self):
        """Blocking copy to host numpy (the reference's big sync point)."""
        return _np.asarray(jax.device_get(self.data))

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("the array is not a scalar (shape %s)" % (self.shape,))
        return self.asnumpy().reshape(())[()]

    def astype(self, dtype):
        res = empty(self.shape, ctx=self._ctx, dtype=dtype)
        self.copyto(res)
        return res

    # ------------------------------------------------------------------
    # copy / context movement (CopyFromTo, src/ndarray/ndarray.cc:286)
    # ------------------------------------------------------------------
    def copyto(self, other):
        if isinstance(other, NDArray):
            if other is self:
                return other
            other._set_data(self.data.astype(other.dtype))
            return other
        if isinstance(other, Context):
            ret = NDArray(self.data, ctx=other)
            return ret
        raise MXNetError("copyto does not support type %s" % type(other))

    def copy(self):
        return self.copyto(self._ctx)

    def as_in_context(self, context):
        if self._ctx == context:
            return self
        return self.copyto(context)

    # ------------------------------------------------------------------
    # views: slice/at/reshape (zero-copy in the reference,
    # include/mxnet/ndarray.h:241-275; here write-through views)
    # ------------------------------------------------------------------
    def slice(self, start, stop):
        start, stop = int(start), int(stop)
        return NDArray(None, _parent=self, _getter=lambda d: d[start:stop],
                       _setter=lambda d, v: d.at[start:stop].set(v),
                       writable=self._writable)

    def at(self, idx):
        idx = int(idx)
        return NDArray(None, _parent=self, _getter=lambda d: d[idx],
                       _setter=lambda d, v: d.at[idx].set(v),
                       writable=self._writable)

    def reshape(self, shape):
        shape = tuple(int(s) for s in shape)
        # -1 wildcard
        if any(s == -1 for s in shape):
            known = 1
            for s in shape:
                if s != -1:
                    known *= s
            shape = tuple(self.size // known if s == -1 else s for s in shape)
        if _np.prod(shape, dtype=_np.int64) != self.size:
            raise MXNetError("reshape size mismatch %s -> %s" % (self.shape, shape))
        parent_shape = self.shape
        return NDArray(None, _parent=self,
                       _getter=lambda d: d.reshape(shape),
                       _setter=lambda d, v: v.reshape(parent_shape),
                       writable=self._writable)

    @property
    def T(self):
        return transpose(self)

    # ------------------------------------------------------------------
    # indexing
    # ------------------------------------------------------------------
    def __getitem__(self, key):
        if isinstance(key, int):
            return self.at(key)
        if isinstance(key, slice):
            if key.step is not None and key.step != 1:
                raise MXNetError("slice step not supported")
            start = key.start if key.start is not None else 0
            stop = key.stop if key.stop is not None else self.shape[0]
            return self.slice(start, stop)
        raise MXNetError("NDArray only supports int and contiguous slice indexing; "
                         "use .asnumpy() for fancy indexing")

    def __setitem__(self, key, value):
        if not self._writable:
            raise MXNetError("trying to write to a read-only NDArray")
        if isinstance(key, slice) and key.start is None and key.stop is None:
            if isinstance(value, NDArray):
                value = value.data
            self._set_data(value)
            return
        view = self[key]
        if isinstance(value, NDArray):
            value = value.data
        view._set_data(value)

    # ------------------------------------------------------------------
    # arithmetic (imperative path; parity src/ndarray/ndarray.cc:96-225)
    # ------------------------------------------------------------------
    def _binary(self, other, fn, reverse=False):
        rhs = other.data if isinstance(other, NDArray) else other
        lhs = self.data
        if reverse:
            lhs, rhs = rhs, lhs
        return NDArray(fn(lhs, rhs), ctx=self._ctx)

    def __add__(self, other):
        return self._binary(other, jnp.add)

    __radd__ = __add__

    def __sub__(self, other):
        return self._binary(other, jnp.subtract)

    def __rsub__(self, other):
        return self._binary(other, jnp.subtract, reverse=True)

    def __mul__(self, other):
        return self._binary(other, jnp.multiply)

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binary(other, jnp.divide)

    def __rtruediv__(self, other):
        return self._binary(other, jnp.divide, reverse=True)

    __div__ = __truediv__
    __rdiv__ = __rtruediv__

    def __pow__(self, other):
        return self._binary(other, jnp.power)

    def __rpow__(self, other):
        return self._binary(other, jnp.power, reverse=True)

    def __neg__(self):
        return NDArray(-self.data, ctx=self._ctx)

    def __eq__(self, other):
        return self._binary(other, lambda a, b: (a == b).astype(a.dtype))

    def __ne__(self, other):
        return self._binary(other, lambda a, b: (a != b).astype(a.dtype))

    def __gt__(self, other):
        return self._binary(other, lambda a, b: (a > b).astype(a.dtype))

    def __ge__(self, other):
        return self._binary(other, lambda a, b: (a >= b).astype(a.dtype))

    def __lt__(self, other):
        return self._binary(other, lambda a, b: (a < b).astype(a.dtype))

    def __le__(self, other):
        return self._binary(other, lambda a, b: (a <= b).astype(a.dtype))

    def __hash__(self):
        return id(self)

    def __bool__(self):
        raise MXNetError("NDArray truth value is ambiguous; use .asscalar()")

    # in-place: rebind buffer (write-through for views)
    def _inplace(self, other, fn):
        rhs = other.data if isinstance(other, NDArray) else other
        self._set_data(fn(self.data, rhs))
        return self

    def __iadd__(self, other):
        return self._inplace(other, jnp.add)

    def __isub__(self, other):
        return self._inplace(other, jnp.subtract)

    def __imul__(self, other):
        return self._inplace(other, jnp.multiply)

    def __itruediv__(self, other):
        return self._inplace(other, jnp.divide)

    __idiv__ = __itruediv__


# ----------------------------------------------------------------------
# creation functions (python/mxnet/ndarray.py zeros/ones/array/... parity)
# ----------------------------------------------------------------------
def _as_shape(shape):
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s) for s in shape)


def empty(shape, ctx=None, dtype=mx_real_t):
    return NDArray(jnp.empty(_as_shape(shape), dtype=dtype), ctx=ctx)


def zeros(shape, ctx=None, dtype=mx_real_t):
    return NDArray(jnp.zeros(_as_shape(shape), dtype=dtype), ctx=ctx)


def ones(shape, ctx=None, dtype=mx_real_t):
    return NDArray(jnp.ones(_as_shape(shape), dtype=dtype), ctx=ctx)


def full(shape, val, ctx=None, dtype=mx_real_t):
    return NDArray(jnp.full(_as_shape(shape), val, dtype=dtype), ctx=ctx)


def array(source_array, ctx=None, dtype=None):
    if isinstance(source_array, NDArray):
        src = source_array.data
        dtype = dtype or src.dtype
    else:
        src = _np.asarray(source_array)
        dtype = dtype or (src.dtype if src.dtype != _np.float64 else mx_real_t)
    return NDArray(jnp.asarray(src, dtype=dtype), ctx=ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=mx_real_t):
    arr = jnp.arange(start, stop, step, dtype=dtype)
    if repeat != 1:
        arr = jnp.repeat(arr, repeat)
    return NDArray(arr, ctx=ctx)


def concatenate(arrays, axis=0, always_copy=True):
    if not always_copy and len(arrays) == 1:
        return arrays[0]
    return NDArray(jnp.concatenate([a.data for a in arrays], axis=axis),
                   ctx=arrays[0].context)


def waitall():
    """Block until all launched work completes (Engine::WaitForAll parity):
    drains the host-side dependency engine (prefetch/decode/checkpoint
    pushes), then blocks on every live NDArray's buffer — a weak registry
    of this framework's arrays, not a scan of the whole process heap."""
    from . import engine as _engine
    eng = _engine._ENGINE
    if eng is not None:
        eng.wait_for_all()
    with _LIVE_LOCK:
        live = list(_LIVE)
    for arr in live:
        data = arr._storage
        if data is not None and hasattr(data, "block_until_ready"):
            try:
                data.block_until_ready()
            except Exception:
                pass


# ----------------------------------------------------------------------
# registered functions (parity: src/ndarray/ndarray.cc:783-944 table)
# ----------------------------------------------------------------------
def _unary(fn):
    def wrapped(data, out=None):
        res = fn(data.data)
        if out is not None:
            out._set_data(res)
            return out
        return NDArray(res, ctx=data.context)
    return wrapped


sqrt = _unary(jnp.sqrt)
rsqrt = _unary(lambda x: 1.0 / jnp.sqrt(x))
exp = _unary(jnp.exp)
log = _unary(jnp.log)
cos = _unary(jnp.cos)
sin = _unary(jnp.sin)
abs = _unary(jnp.abs)  # noqa: A001 - parity with mx.nd.abs
sign = _unary(jnp.sign)
round = _unary(jnp.round)  # noqa: A001
ceil = _unary(jnp.ceil)
floor = _unary(jnp.floor)
square = _unary(jnp.square)


def negative(data, out=None):
    return _unary(jnp.negative)(data, out)


def dot(lhs, rhs, out=None):
    """2-D matrix product (simple op ``dot``, src/operator/matrix_op*)."""
    res = jnp.dot(lhs.data, rhs.data, preferred_element_type=lhs.dtype)
    if out is not None:
        out._set_data(res)
        return out
    return NDArray(res, ctx=lhs.context)


def batch_dot(lhs, rhs, out=None):
    res = jnp.matmul(lhs.data, rhs.data)
    if out is not None:
        out._set_data(res)
        return out
    return NDArray(res, ctx=lhs.context)


def clip(data, a_min, a_max, out=None):
    res = jnp.clip(data.data, a_min, a_max)
    if out is not None:
        out._set_data(res)
        return out
    return NDArray(res, ctx=data.context)


def add(lhs, rhs):
    """Elementwise sum, either operand NDArray or scalar (reference
    ndarray.py add)."""
    return lhs + rhs if isinstance(lhs, NDArray) else rhs + lhs


def subtract(lhs, rhs):
    if isinstance(lhs, NDArray):
        return lhs - rhs
    return rhs.__rsub__(lhs)


def multiply(lhs, rhs):
    return lhs * rhs if isinstance(lhs, NDArray) else rhs * lhs


def divide(lhs, rhs):
    if isinstance(lhs, NDArray):
        return lhs / rhs
    return rhs.__rtruediv__(lhs)


true_divide = divide


def power(lhs, rhs):
    if isinstance(lhs, NDArray):
        return lhs ** rhs
    return rhs.__rpow__(lhs)


def maximum(lhs, rhs):
    l = lhs.data if isinstance(lhs, NDArray) else lhs
    r = rhs.data if isinstance(rhs, NDArray) else rhs
    ctx = lhs.context if isinstance(lhs, NDArray) else rhs.context
    return NDArray(jnp.maximum(l, r), ctx=ctx)


def minimum(lhs, rhs):
    l = lhs.data if isinstance(lhs, NDArray) else lhs
    r = rhs.data if isinstance(rhs, NDArray) else rhs
    ctx = lhs.context if isinstance(lhs, NDArray) else rhs.context
    return NDArray(jnp.minimum(l, r), ctx=ctx)


def sum(data, axis=None, keepdims=False):  # noqa: A001
    return NDArray(jnp.sum(data.data, axis=axis, keepdims=keepdims), ctx=data.context)


def max(data, axis=None, keepdims=False):  # noqa: A001
    return NDArray(jnp.max(data.data, axis=axis, keepdims=keepdims), ctx=data.context)


def min(data, axis=None, keepdims=False):  # noqa: A001
    return NDArray(jnp.min(data.data, axis=axis, keepdims=keepdims), ctx=data.context)


def argmax(data, axis=None, keepdims=False):
    res = jnp.argmax(data.data, axis=axis, keepdims=keepdims).astype(data.dtype)
    return NDArray(res, ctx=data.context)


def argmax_channel(data):
    """argmax over axis 1 (channel), parity with the reference simple op."""
    return NDArray(jnp.argmax(data.data, axis=1).astype(data.dtype), ctx=data.context)


def norm(data):
    return NDArray(jnp.sqrt(jnp.sum(jnp.square(data.data))), ctx=data.context)


def transpose(data, axes=None):
    return NDArray(jnp.transpose(data.data, axes=axes), ctx=data.context)


def swapaxes(data, dim1, dim2):
    return NDArray(jnp.swapaxes(data.data, dim1, dim2), ctx=data.context)


def expand_dims(data, axis):
    return NDArray(jnp.expand_dims(data.data, axis), ctx=data.context)


def flip(data, axis):
    return NDArray(jnp.flip(data.data, axis), ctx=data.context)


def crop(data, begin, end):
    idx = tuple(slice(b, e) for b, e in zip(begin, end))
    return NDArray(data.data[idx], ctx=data.context)


def slice_axis(data, axis, begin, end):
    idx = [slice(None)] * data.ndim
    if end is None or end == 0:
        end = data.shape[axis]
    idx[axis] = slice(begin, end)
    return NDArray(data.data[tuple(idx)], ctx=data.context)


def broadcast_to(data, shape):
    return NDArray(jnp.broadcast_to(data.data, _as_shape(shape)), ctx=data.context)


def broadcast_axis(data, axis, size):
    axes = axis if isinstance(axis, (list, tuple)) else (axis,)
    sizes = size if isinstance(size, (list, tuple)) else (size,)
    shape = list(data.shape)
    for ax, s in zip(axes, sizes):
        shape[ax] = s
    return broadcast_to(data, shape)


def smooth_l1(data, scalar=1.0):
    """Huber-ish loss used by Faster R-CNN (src/operator/smooth_l1_unary*)."""
    sigma2 = scalar * scalar
    x = data.data
    res = jnp.where(jnp.abs(x) < 1.0 / sigma2,
                    0.5 * sigma2 * jnp.square(x),
                    jnp.abs(x) - 0.5 / sigma2)
    return NDArray(res, ctx=data.context)


def softmax_cross_entropy(data, label):
    """Simple op ``softmax_cross_entropy`` (scalar output)."""
    logits = data.data
    lab = label.data.astype(jnp.int32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, lab[:, None], axis=-1)
    return NDArray(jnp.sum(nll), ctx=data.context)


def onehot_encode(indices, out):
    """_onehot_encode (ndarray.cc:795): out[i, indices[i]] = 1."""
    depth = out.shape[1]
    res = jax.nn.one_hot(indices.data.astype(jnp.int32), depth, dtype=out.dtype)
    out._set_data(res)
    return out


def choose_element_0index(lhs, rhs, out=None):
    """out[i] = lhs[i, rhs[i]] (ndarray.cc registered fn)."""
    idx = rhs.data.astype(jnp.int32)
    res = jnp.take_along_axis(lhs.data, idx[:, None], axis=1)[:, 0]
    if out is not None:
        out._set_data(res)
        return out
    return NDArray(res, ctx=lhs.context)


def fill_element_0index(lhs, mhs, rhs, out=None):
    """out = lhs with out[i, rhs[i]] = mhs[i] (three-operand fill)."""
    idx = rhs.data.astype(jnp.int32)
    res = lhs.data.at[jnp.arange(lhs.shape[0]), idx].set(mhs.data)
    if out is not None:
        out._set_data(res)
        return out
    return NDArray(res, ctx=lhs.context)


def elementwise_sum(arrays, out=None):
    """ElementwiseSum (src/ndarray/ndarray.cc:352)."""
    res = arrays[0].data
    for a in arrays[1:]:
        res = res + a.data
    if out is not None:
        out._set_data(res)
        return out
    return NDArray(res, ctx=arrays[0].context)


add_n = elementwise_sum


def imdecode(str_img, clip_rect=(0, 0, 0, 0), out=None, index=0, channels=3, mean=None):
    """Decode an image buffer (gated: needs PIL or cv2; parity _imdecode)."""
    import io as _io
    try:
        from PIL import Image  # type: ignore
        img = _np.asarray(Image.open(_io.BytesIO(str_img)).convert("RGB"))
    except ImportError:
        raise MXNetError("imdecode requires PIL (not available)")
    img = img.transpose(2, 0, 1).astype(mx_real_t)  # HWC -> CHW
    if mean is not None:
        img = img - mean.asnumpy()
    if clip_rect != (0, 0, 0, 0):
        x0, y0, x1, y1 = clip_rect
        img = img[:, y0:y1, x0:x1]
    res = array(img[None])
    if out is not None:
        out._set_data(res.data)
        return out
    return res


# ----------------------------------------------------------------------
# save / load (parity: src/ndarray/ndarray.cc:637-700; magic 0x112)
# ----------------------------------------------------------------------
_MAGIC = 0x112
_RESERVED = 0


def _write_str(fo, s):
    b = s.encode("utf-8")
    fo.write(struct.pack("<Q", len(b)))
    fo.write(b)


def _read_str(fi):
    (n,) = struct.unpack("<Q", fi.read(8))
    return fi.read(n).decode("utf-8")


def _save_one(fo, arr: NDArray):
    # TShape: uint32 ndim + uint32 dims (mshadow layout)
    fo.write(struct.pack("<I", arr.ndim))
    fo.write(struct.pack("<%dI" % arr.ndim, *arr.shape))
    # Context: int32 dev_type, int32 dev_id (include/mxnet/base.h:85)
    fo.write(struct.pack("<ii", arr.context.device_typeid, arr.context.device_id))
    # type flag + raw data
    npy = arr.asnumpy()
    fo.write(struct.pack("<i", dtype_np_to_mx(npy.dtype)))
    fo.write(npy.tobytes())


def _load_one(fi) -> NDArray:
    (ndim,) = struct.unpack("<I", fi.read(4))
    shape = struct.unpack("<%dI" % ndim, fi.read(4 * ndim)) if ndim else ()
    dev_type, dev_id = struct.unpack("<ii", fi.read(8))
    (flag,) = struct.unpack("<i", fi.read(4))
    dtype = dtype_mx_to_np(flag)
    count = int(_np.prod(shape, dtype=_np.int64)) if shape else 1
    buf = fi.read(count * dtype.itemsize)
    npy = _np.frombuffer(buf, dtype=dtype).reshape(shape)
    # arrays load onto the default context (GPU/TPU arrays were staged via CPU)
    return array(npy, dtype=dtype)


def save(fname, data):
    """Save NDArrays (save_checkpoint file format): a str->NDArray dict,
    a list of arrays, or a list of (name, array) pairs.  Caller's order
    is the file's order, duplicates included — the reference
    MXNDArraySave writes names exactly as given."""
    if isinstance(data, NDArray):
        data = [data]
    names = []
    arrays = []
    if isinstance(data, dict):
        for k in data:
            names.append(k)
            arrays.append(data[k])
    elif data and all(isinstance(item, tuple) and len(item) == 2
                      for item in data):
        for k, v in data:
            names.append(k)
            arrays.append(v)
    else:
        arrays = list(data)
    from .stream import open_uri
    with open_uri(fname, "wb") as fo:
        fo.write(struct.pack("<QQ", _MAGIC, _RESERVED))
        fo.write(struct.pack("<Q", len(arrays)))
        for arr in arrays:
            _save_one(fo, arr)
        fo.write(struct.pack("<Q", len(names)))
        for name in names:
            _write_str(fo, name)


def load_raw(fname):
    """-> (names, arrays) exactly as stored — duplicates and file order
    preserved (the C ABI's MXNDArrayLoad contract)."""
    from .stream import open_uri
    with open_uri(fname, "rb") as fi:
        magic, _ = struct.unpack("<QQ", fi.read(16))
        if magic != _MAGIC:
            raise MXNetError("invalid NDArray file %s (bad magic)" % fname)
        (n,) = struct.unpack("<Q", fi.read(8))
        arrays = [_load_one(fi) for _ in range(n)]
        (m,) = struct.unpack("<Q", fi.read(8))
        names = [_read_str(fi) for _ in range(m)]
    return names, arrays


def load(fname):
    names, arrays = load_raw(fname)
    if names:
        return dict(zip(names, arrays))
    return arrays
