"""Attribute scoping for symbols (parity: python/mxnet/attribute.py).

``with mx.AttrScope(ctx_group='stage1'):`` attaches attrs to every symbol
created inside — the mechanism behind ctx-group model parallelism
(SURVEY §2 "Parallelism strategies": example/model-parallel-lstm/lstm.py:48-99).
"""
from __future__ import annotations

import threading

__all__ = ["AttrScope"]


class AttrScope:
    _current = threading.local()

    def __init__(self, **kwargs):
        for value in kwargs.values():
            if not isinstance(value, str):
                raise ValueError("attributes must be strings")
        self._attr = kwargs
        self._old_scope = None

    def get(self, attr):
        """Merge scope attrs into user-supplied ``attr`` dict (user wins)."""
        if self._attr:
            ret = self._attr.copy()
            if attr:
                ret.update(attr)
            return ret
        return attr if attr else {}

    def __enter__(self):
        if not hasattr(AttrScope._current, "value"):
            AttrScope._current.value = AttrScope()
        self._old_scope = AttrScope._current.value
        attr = AttrScope._current.value._attr.copy()
        attr.update(self._attr)
        self._attr = attr
        AttrScope._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        AttrScope._current.value = self._old_scope

    @staticmethod
    def current():
        if not hasattr(AttrScope._current, "value"):
            AttrScope._current.value = AttrScope()
        return AttrScope._current.value


def mirror_scope(stage_name, enabled=True):
    """Attr scope tagging every op created inside it for activation
    recompute: ``force_mirroring`` (overrides the env knob's conv skip
    list) + ``mirror_stage=stage_name`` (segment boundary — ops sharing
    a stage form ONE jax.checkpoint segment in the executor's mirror
    lowering, executor.py ``_mirror_segments``).  ``enabled=False``
    returns a no-op context so model builders can expose a
    ``mirror_blocks`` flag without branching (models/resnet.py,
    models/transformer.py)."""
    if not enabled:
        import contextlib
        return contextlib.nullcontext()
    return AttrScope(force_mirroring="true", mirror_stage=stage_name)
