"""Attribute scoping for symbols (parity: python/mxnet/attribute.py).

``with mx.AttrScope(ctx_group='stage1'):`` attaches attrs to every symbol
created inside — the mechanism behind ctx-group model parallelism
(SURVEY §2 "Parallelism strategies": example/model-parallel-lstm/lstm.py:48-99).
"""
from __future__ import annotations

import threading

__all__ = ["AttrScope"]


class AttrScope:
    _current = threading.local()

    def __init__(self, **kwargs):
        for value in kwargs.values():
            if not isinstance(value, str):
                raise ValueError("attributes must be strings")
        self._attr = kwargs
        self._old_scope = None

    def get(self, attr):
        """Merge scope attrs into user-supplied ``attr`` dict (user wins)."""
        if self._attr:
            ret = self._attr.copy()
            if attr:
                ret.update(attr)
            return ret
        return attr if attr else {}

    def __enter__(self):
        if not hasattr(AttrScope._current, "value"):
            AttrScope._current.value = AttrScope()
        self._old_scope = AttrScope._current.value
        attr = AttrScope._current.value._attr.copy()
        attr.update(self._attr)
        self._attr = attr
        AttrScope._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        AttrScope._current.value = self._old_scope

    @staticmethod
    def current():
        if not hasattr(AttrScope._current, "value"):
            AttrScope._current.value = AttrScope()
        return AttrScope._current.value
