"""MXL-X: retrace-stability lint — statically prove the
zero-steady-state-lowerings contract.

Every perf tentpole since the program registry (docs/perf.md
"Overlap", compile cache) rests on one invariant: serving, generation,
hot-swap and elastic re-mesh must perform ZERO steady-state lowerings.
The runtime enforces it with registry counters inside a handful of
drills; this pass enforces it at lint time, over the source, so a
retrace hazard introduced anywhere ships as a CI finding instead of a
burned chip window.

Same pure-AST driver shape as the MXL-D divergence and MXL-Q
concurrency passes: parse, never import.  Rules:

- **MXL-X001** — python ``if``/``while`` (or a host materialization
  like ``float()``/``.item()``) on a tensor-derived value inside a
  traced scope.  Every distinct runtime value forces a fresh trace —
  the per-value retrace that turns a steady-state server into a
  compile loop.  Traced scopes are inferred from same-file
  ``jax.jit``/``pjit``/``pallas_call``/``jax.checkpoint``/``jax.vjp``
  sites and jit decorators; mark indirect ones with
  ``base.traced_scope``.  ``static_argnames`` params are exempt (they
  are host values by contract).
- **MXL-X002** — unstable cache-key ingredient: ``id(...)`` in a key
  (identity is recycled after gc and never survives a rebuild),
  unsorted ``dict``/``set`` iteration (``.items()``/``.keys()``/
  ``.values()``/``set(...)`` outside ``sorted(...)``) flowing into a
  key, or an environment read inside a traced function body (the value
  bakes at trace time — a later flip silently no-ops OR retraces).
  Audits ``overlap.cache_key`` call sites, ``*key`` assignments and
  ``*cache*``/``*registry*`` subscripts.
- **MXL-X003** — ``jax.jit``/AOT ``.lower`` constructed on a
  per-request or per-step path (or inside a loop) without going
  through the program registry.  Builders (``_build*``/``__init__``/
  warmup/compile/lower) and memoized once-only constructions
  (``if x is None:`` / ``if key not in cache:`` guards) are exempt, as
  is any function that itself calls the registry API
  (``_lookup_program``/``compile_cache_get``/``note_lowering``).
- **MXL-X004** — bare python scalar passed positionally to a jitted
  entry point (a ``_jit*`` attribute or a name bound from
  ``jax.jit``).  Weak-type flapping — a python float one call, an
  array the next — changes the abstract signature and retraces; wrap
  with ``jnp.asarray(v, dtype)`` (the executor's ``jnp.float32(lr)``
  idiom) or make the argument static.
- **MXL-X005** — dynamic size (``len(...)``/``.shape``) indexing an
  AOT program table (``_prefill``/``_decode``/``predictors``) without
  bucket routing.  Serving must pick the program with
  ``buckets.bucket_for``/``prefill_bucket``; a novel size otherwise
  lowers a fresh program per request.
- **MXL-X006** — donated buffer read after donation:
  ``jit(..., donate_argnums=...)`` invalidates the donated argument;
  reading it afterwards (instead of the returned replacement) is
  undefined behavior that surfaces as corrupt results or a retrace.

Suppress intentional violations with ``# mxl: retrace-ok (MXL-X00n)``
on the finding line, the line above, or the enclosing ``def``.  The
runtime witness for this family is ``observability.retrace``
(``MXTPU_RETRACE_SENTRY=1``), which counts and *attributes* every
post-warmup lowering.  See docs/graph_lint.md (MXL-X).
"""
from __future__ import annotations

import ast
import os
import re

from .core import register_rule
from .divergence import iter_py_files, _parse, _dotted, _call_name

__all__ = ["traced_scope", "analyze_retrace_paths", "SUPPRESS_RE"]

# canonical home is base.py (leaf module); re-exported for symmetry
# with divergence.collective_seam / concurrency.thread_entry
from ..base import traced_scope  # noqa: E402,F401


# ----------------------------------------------------------------------
# vocabulary
# ----------------------------------------------------------------------
SUPPRESS_RE = re.compile(
    r"#\s*mxl:\s*retrace-ok(?:\s*\(([^)]*)\))?")

_TRACED_DECORATOR = "traced_scope"

#: call names whose function argument becomes a traced scope
_JIT_WRAPPERS = {"jit", "pjit"}
_TRACE_WRAPPERS = _JIT_WRAPPERS | {"pallas_call", "checkpoint", "remat",
                                   "vjp", "vmap", "value_and_grad",
                                   "grad"}

#: builtins that materialize a tracer on the host (concretization)
_HOST_COERCIONS = {"float", "int", "bool"}
_HOST_METHODS = {"item", "tolist", "numpy"}
_HOST_ARRAY_FNS = {"asarray", "array"}       # under an np/numpy prefix

#: attribute reads that yield STATIC facts about a tracer (shape-land)
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "sharding",
                 "aval", "weak_type"}

#: calls whose result is a host/static value even on tainted input
_TAINT_SANITIZERS = {"len", "isinstance", "type", "range", "hash",
                     "getattr", "hasattr", "id", "str", "repr",
                     "format", "callable"}

#: cache-key contexts audited by X002
_KEYISH_RE = re.compile(r"(^|_)(g?key|cache_key|ckey|fused_key)$", re.I)
_CACHEISH_RE = re.compile(r"cache|registry", re.I)
_ITER_ORDER_CALLS = {"keys", "values", "items"}
_SET_FACTORIES = {"set", "frozenset"}

#: X003 function-name vocabulary
_PER_STEP_RE = re.compile(
    r"forward|predict|generate|decode|prefill|submit|dispatch|sample|"
    r"request|handle|complete|step|run", re.I)
_BUILDER_RE = re.compile(
    r"build|init|warmup|compile|lower|aot|probe|setup|register|create|"
    r"make|load|swap|symbol", re.I)
_REGISTRY_API = {"_lookup_program", "compile_cache_get",
                 "compile_cache_put", "note_lowering", "note_hit"}

#: X005 program tables + bucket routing
_PROGRAM_TABLE_RE = re.compile(
    r"(_prefill|_decode|predictor|_program|program_table)s?$", re.I)
_BUCKET_CALL_RE = re.compile(r"bucket", re.I)


# ----------------------------------------------------------------------
# small helpers
# ----------------------------------------------------------------------
def _suppressions(source):
    """line -> set of rule ids (or {'all'}) from retrace-ok marker
    comments."""
    out = {}
    for i, line in enumerate(source.splitlines(), 1):
        m = SUPPRESS_RE.search(line)
        if not m:
            continue
        ids = {s.strip() for s in (m.group(1) or "").split(",")
               if s.strip()}
        out[i] = ids or {"all"}
    return out


def _functions(tree):
    """Yield (qualname, node) for every function at ANY nesting depth
    (traced bodies are almost always nested defs: ``trace`` inside
    ``_build_program``, ``step`` inside ``_build_fused_step``)."""
    out = []

    def _walk(nodes, prefix):
        for n in nodes:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = prefix + n.name
                out.append((q, n))
                _walk(n.body, q + ".")
            elif isinstance(n, ast.ClassDef):
                _walk(n.body, prefix + n.name + ".")
    _walk(tree.body, "")
    return out


def _decorators(fn):
    out = set()
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call):
            name = _call_name(dec)
        elif isinstance(dec, ast.Attribute):
            name = dec.attr
        elif isinstance(dec, ast.Name):
            name = dec.id
        else:
            name = None
        if name:
            out.add(name)
    return out


def _static_argnames(call):
    """Literal ``static_argnames=`` entries of a jit call site."""
    for kw in call.keywords:
        if kw.arg != "static_argnames":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            return {v.value}
        if isinstance(v, (ast.Tuple, ast.List)):
            return {e.value for e in v.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)}
    return set()


def _donate_argnums(call):
    """Literal ``donate_argnums=`` tuple of a jit call site, or None."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            return tuple(e.value for e in v.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, int))
    return None


def _shallow_stmts(body):
    """Walk statements/expressions of one scope body without
    descending into nested function/class scopes."""
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _traced_defs(tree):
    """{def node -> static argname set} for every function the file
    hands to a trace wrapper (``jax.jit(trace, ...)``,
    ``jax.checkpoint(seg_fn)``, ``pl.pallas_call(kernel, ...)``).

    Resolution is lexical, innermost scope first — ``jax.jit(step)``
    inside ``_build`` marks the nested ``step`` def, NOT an unrelated
    host-side method that happens to share the name elsewhere in the
    file."""
    traced = {}

    def _scan(body, frames):
        local = {n.name: n for n in _shallow_stmts(body)
                 if isinstance(n, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))}
        frames = frames + [local]
        for node in _shallow_stmts(body):
            if isinstance(node, ast.Call):
                wrapper = _call_name(node)
                if wrapper not in _TRACE_WRAPPERS:
                    continue
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        for frame in reversed(frames):
                            if arg.id in frame:
                                static = (_static_argnames(node)
                                          if wrapper in _JIT_WRAPPERS
                                          else set())
                                traced.setdefault(
                                    frame[arg.id], set()).update(static)
                                break
                        break       # only the first fn-valued argument
        for node in _shallow_stmts(body):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                _scan(node.body, frames)

    _scan(tree.body, [])
    return traced


def _is_aot_lower(call):
    """``something.lower(args...)`` with at least one argument — the
    AOT entry.  ``str.lower()`` takes no arguments, so the arity test
    alone separates the two meanings."""
    return (isinstance(call.func, ast.Attribute)
            and call.func.attr == "lower"
            and bool(call.args or call.keywords))


# ----------------------------------------------------------------------
# taint: tensor-derived values inside a traced scope (X001)
# ----------------------------------------------------------------------
def _compare_is_identity(node):
    """True for comparisons that stay host-static on tracers:
    ``is``/``is not``/``in``/``not in``."""
    return all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
               for op in node.ops)


def _tainted(node, tainted):
    """Does ``node`` (an expression) carry a tensor-derived value?"""
    if isinstance(node, ast.Name):
        return node.id in tainted
    if isinstance(node, ast.Constant):
        return False
    if isinstance(node, ast.Lambda):
        return False
    if isinstance(node, ast.Attribute):
        if node.attr in _STATIC_ATTRS:
            return False
        return _tainted(node.value, tainted)
    if isinstance(node, ast.Compare):
        if _compare_is_identity(node):
            return False
        return any(_tainted(c, tainted)
                   for c in [node.left] + node.comparators)
    if isinstance(node, ast.Call):
        name = _call_name(node)
        if name in _TAINT_SANITIZERS:
            return False
        parts = list(node.args) + [kw.value for kw in node.keywords]
        if isinstance(node.func, ast.Attribute):
            parts.append(node.func.value)
        return any(_tainted(p, tainted) for p in parts)
    if isinstance(node, ast.BoolOp):
        return any(_tainted(v, tainted) for v in node.values)
    if isinstance(node, (ast.BinOp, ast.UnaryOp, ast.IfExp, ast.Tuple,
                         ast.List, ast.Set, ast.Subscript, ast.Starred,
                         ast.Slice, ast.JoinedStr, ast.FormattedValue,
                         ast.Dict, ast.GeneratorExp, ast.ListComp,
                         ast.SetComp, ast.DictComp)):
        return any(_tainted(c, tainted) for c in ast.iter_child_nodes(node)
                   if isinstance(c, ast.expr))
    return False


def _target_names(target):
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out = []
        for e in target.elts:
            out.extend(_target_names(e))
        return out
    if isinstance(target, ast.Starred):
        return _target_names(target.value)
    return []


def _collect_taint(fn, static):
    """Fixpoint taint set for one traced function: params (minus the
    static argnames) seed it; assignments propagate it."""
    args = fn.args
    params = [a.arg for a in
              list(args.posonlyargs) + list(args.args)
              + list(args.kwonlyargs)]
    if args.vararg:
        params.append(args.vararg.arg)
    if args.kwarg:
        params.append(args.kwarg.arg)
    tainted = {p for p in params
               if p not in static and p not in ("self", "cls")}
    for _ in range(6):
        grew = False
        for node in ast.walk(fn):
            targets = []
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                targets, value = [node.target], node.value
            elif isinstance(node, ast.NamedExpr):
                targets, value = [node.target], node.value
            elif isinstance(node, ast.For):
                targets, value = [node.target], node.iter
            else:
                continue
            if value is None or not _tainted(value, tainted):
                continue
            for t in targets:
                for name in _target_names(t):
                    if name not in tainted:
                        tainted.add(name)
                        grew = True
        if not grew:
            break
    return tainted


def _traced_scope_findings(fn, qual, static):
    """X001 + X002(env-read) over one traced function body."""
    out = []
    tainted = _collect_taint(fn, static)
    for node in ast.walk(fn):
        if isinstance(node, (ast.If, ast.While)) and \
                _tainted(node.test, tainted):
            kind = "if" if isinstance(node, ast.If) else "while"
            out.append((
                "MXL-X001", node.lineno, qual,
                "python `%s` on a tensor-derived value inside a traced "
                "scope — every distinct runtime value forces a fresh "
                "trace (per-value retrace); use lax.cond/jnp.where or "
                "hoist the decision before tracing" % kind))
        elif isinstance(node, ast.Call):
            name = _call_name(node)
            dotted = _dotted(node.func) or ""
            if isinstance(node.func, ast.Name) and \
                    name in _HOST_COERCIONS and \
                    any(_tainted(a, tainted) for a in node.args):
                out.append((
                    "MXL-X001", node.lineno, qual,
                    "%s() materializes a tensor-derived value on the "
                    "host inside a traced scope — concretization "
                    "either fails to trace or bakes one value per "
                    "compile; keep the math in jnp" % name))
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _HOST_METHODS and \
                    _tainted(node.func.value, tainted):
                out.append((
                    "MXL-X001", node.lineno, qual,
                    ".%s() materializes a tensor-derived value on the "
                    "host inside a traced scope — concretization "
                    "forces a per-value retrace" % node.func.attr))
            elif name in _HOST_ARRAY_FNS and \
                    dotted.split(".")[0] in ("np", "numpy") and \
                    any(_tainted(a, tainted) for a in node.args):
                out.append((
                    "MXL-X001", node.lineno, qual,
                    "numpy.%s on a tensor-derived value inside a "
                    "traced scope pulls the tracer to the host; use "
                    "jnp instead" % name))
            if _is_env_read(node):
                out.append((
                    "MXL-X002", node.lineno, qual,
                    "environment read inside a traced function body — "
                    "the value is baked at trace time, so a later flip "
                    "either silently no-ops or forces a retrace; read "
                    "the env before tracing and close over the result "
                    "(and key any cache on it)"))
        elif isinstance(node, ast.Subscript) and \
                (_dotted(node.value) or "").endswith("environ"):
            out.append((
                "MXL-X002", node.lineno, qual,
                "os.environ[...] inside a traced function body — the "
                "value is baked at trace time; hoist the read out of "
                "the traced scope"))
    return out


def _is_env_read(call):
    dotted = _dotted(call.func) or ""
    return (dotted.endswith("environ.get") or dotted.endswith("getenv")
            or dotted.endswith("environ.setdefault"))


# ----------------------------------------------------------------------
# cache-key hygiene (X002)
# ----------------------------------------------------------------------
def _unstable_key_parts(expr):
    """Yield (lineno, message) for unstable ingredients inside one
    cache-key expression: ``id(...)`` anywhere, and dict/set iteration
    order not laundered through ``sorted(...)``."""
    def _walk(node, under_sorted):
        if isinstance(node, ast.Call):
            name = _call_name(node)
            if name == "id" and isinstance(node.func, ast.Name):
                yield (node.lineno,
                       "id() in a cache key — object identity is "
                       "recycled after gc and never matches across "
                       "rebuilds, so a logically identical object "
                       "misses (needless retrace) or a recycled id "
                       "falsely hits (stale program); key on a value "
                       "fingerprint (overlap.optimizer_fingerprint / "
                       "overlap.cache_key) instead")
            elif not under_sorted and name in _ITER_ORDER_CALLS and \
                    isinstance(node.func, ast.Attribute):
                yield (node.lineno,
                       ".%s() iteration order flows into a cache key "
                       "unsorted — wrap it in sorted(...) or the same "
                       "mapping can produce two different keys" % name)
            elif not under_sorted and name in _SET_FACTORIES and \
                    isinstance(node.func, ast.Name):
                yield (node.lineno,
                       "set iteration order flows into a cache key — "
                       "wrap it in sorted(...)")
            child_sorted = under_sorted or name == "sorted"
            for c in ast.iter_child_nodes(node):
                yield from _walk(c, child_sorted)
        else:
            for c in ast.iter_child_nodes(node):
                yield from _walk(c, under_sorted)
    yield from _walk(expr, False)


def _expr_has_cacheish(expr):
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and \
                _CACHEISH_RE.search(node.attr):
            return True
        if isinstance(node, ast.Name) and _CACHEISH_RE.search(node.id):
            return True
    return False


def _mentions(expr, name):
    return any(isinstance(n, ast.Name) and n.id == name
               for n in ast.walk(expr))


def _key_feeds_cache(fn, keyname):
    """Does the ``keyname`` local flow into a persistent
    ``*cache*``/``*registry*`` store?  Distinguishes a compile-cache
    key (``self._fused_cache[0] != key`` / ``cache[key] = ...``) from
    the benign per-invocation edge maps (``shapes[(id(node), 0)]``)
    that key live graph nodes by identity for one call's duration."""
    for node in _shallow_walk(fn):
        if isinstance(node, ast.Subscript):
            base = _dotted(node.value) or ""
            if _CACHEISH_RE.search(base.rsplit(".", 1)[-1]) and \
                    _mentions(node.slice, keyname):
                return True
        elif isinstance(node, ast.Compare):
            sides = [node.left] + node.comparators
            if any(_mentions(s, keyname) for s in sides) and \
                    any(_expr_has_cacheish(s) for s in sides):
                return True
        elif isinstance(node, ast.Call):
            dotted = _dotted(node.func) or ""
            owner = dotted.rsplit(".", 1)[0] if "." in dotted else ""
            if _CACHEISH_RE.search(owner) and \
                    any(_mentions(a, keyname) for a in node.args):
                return True
        elif isinstance(node, ast.Assign):
            stores = any(_expr_has_cacheish(t) for t in node.targets)
            if stores and _mentions(node.value, keyname):
                return True
    return False


def _key_hygiene_findings(fn, qual):
    """X002 over one function: audit ``*key =`` assignments whose key
    feeds a cache/registry store, ``cache_key(...)`` call arguments,
    and ``*cache*``/``*registry*`` subscript indexes."""
    out = []
    for node in _shallow_walk(fn):
        exprs = []
        if isinstance(node, ast.Assign):
            names = [n for t in node.targets for n in _target_names(t)]
            if any(_KEYISH_RE.search(n) and _key_feeds_cache(fn, n)
                   for n in names):
                exprs.append(node.value)
        elif isinstance(node, ast.Call) and \
                _call_name(node) == "cache_key":
            exprs.extend(node.args)
        elif isinstance(node, ast.Subscript):
            base = _dotted(node.value) or ""
            if _CACHEISH_RE.search(base.rsplit(".", 1)[-1]):
                exprs.append(node.slice)
        for e in exprs:
            for line, msg in _unstable_key_parts(e):
                out.append(("MXL-X002", line, qual, msg))
    return out


def _shallow_walk(fn):
    """Walk a function body WITHOUT descending into nested defs (each
    nested def gets its own _functions entry, so descending here would
    double-report)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


# ----------------------------------------------------------------------
# per-request jit construction (X003)
# ----------------------------------------------------------------------
def _memo_guarded(test):
    """``if x is None:`` / ``if k not in cache:`` — the once-only
    construction idiom; a jit under such a guard is a lazy memo, not a
    per-call retrace."""
    for node in ast.walk(test):
        if isinstance(node, ast.Compare) and any(
                isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                for op in node.ops):
            return True
        if isinstance(node, ast.UnaryOp) and \
                isinstance(node.op, ast.Not):
            return True
    return False


def _construction_sites(fn):
    """Yield (call, in_loop, guarded, cached_target) for every
    jit/pjit/AOT-lower construction in ``fn`` (nested defs excluded)."""
    def _visit(nodes, in_loop, guarded):
        for node in nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            loop_now = in_loop or isinstance(node, (ast.For, ast.While))
            guard_now = guarded or (isinstance(node, ast.If)
                                    and _memo_guarded(node.test))
            cached = False
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    base = _dotted(t.value if isinstance(t, ast.Subscript)
                                   else t) or ""
                    if _CACHEISH_RE.search(base):
                        cached = True
            for sub in ast.walk(node) if not isinstance(
                    node, (ast.If, ast.For, ast.While, ast.Try,
                           ast.With)) else ():
                if isinstance(sub, ast.Call):
                    name = _call_name(sub)
                    if (name in _JIT_WRAPPERS and
                            isinstance(sub.func, (ast.Name,
                                                  ast.Attribute))) or \
                            _is_aot_lower(sub):
                        yield sub, loop_now, guard_now, cached
            if isinstance(node, ast.If):
                yield from _visit(node.body, loop_now, guard_now)
                yield from _visit(node.orelse, loop_now, guard_now)
            elif isinstance(node, (ast.For, ast.While)):
                yield from _visit(node.body, True, guarded)
                yield from _visit(node.orelse, True, guarded)
            elif isinstance(node, ast.Try):
                for blk in (node.body, node.orelse, node.finalbody):
                    yield from _visit(blk, loop_now, guard_now)
                for h in node.handlers:
                    yield from _visit(h.body, loop_now, guard_now)
            elif isinstance(node, ast.With):
                yield from _visit(node.body, loop_now, guard_now)
    yield from _visit(fn.body, False, False)


def _per_step_jit_findings(fn, qual):
    name = fn.name
    if _BUILDER_RE.search(name):
        return []
    called = {_call_name(n) for n in _shallow_walk(fn)
              if isinstance(n, ast.Call)}
    if called & _REGISTRY_API:
        return []           # registry-aware: this IS the cached path
    per_step = bool(_PER_STEP_RE.search(name))
    out = []
    for call, in_loop, guarded, cached in _construction_sites(fn):
        if guarded or cached:
            continue
        if not (per_step or in_loop):
            continue
        what = ("jit constructed inside a loop"
                if in_loop and not per_step else
                "jit/lower constructed on a per-request/per-step path")
        out.append((
            "MXL-X003", call.lineno, qual,
            "%s — this bypasses the program registry and lowers fresh "
            "on every call; build once (a _build*/__init__ path or an "
            "`is None` memo) or route through "
            "executor._lookup_program / overlap.compile_cache_get so "
            "steady state stays at zero lowerings" % what))
    return out


# ----------------------------------------------------------------------
# weak-type scalar leaks (X004)
# ----------------------------------------------------------------------
def _jitted_local_names(tree):
    """Names bound from ``jax.jit(...)`` anywhere in the file
    (``jit_step = jax.jit(step)``) — the entry points X004 audits in
    addition to ``*._jit*`` attributes."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                _call_name(node.value) in _JIT_WRAPPERS:
            for t in node.targets:
                out.update(_target_names(t))
    return out


def _weak_type_findings(fn, qual, jitted_names):
    out = []
    for node in _shallow_walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        is_jit_entry = False
        if isinstance(f, ast.Attribute) and f.attr.startswith("_jit"):
            is_jit_entry = True
        elif isinstance(f, ast.Name) and f.id in jitted_names:
            is_jit_entry = True
        if not is_jit_entry:
            continue
        for arg in node.args:
            bare = (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, (int, float))
                    and not isinstance(arg.value, bool))
            coerced = (isinstance(arg, ast.Call)
                       and isinstance(arg.func, ast.Name)
                       and arg.func.id in ("float", "int"))
            if bare or coerced:
                out.append((
                    "MXL-X004", node.lineno, qual,
                    "bare python scalar passed positionally to a "
                    "jitted entry point — weak-type flapping (python "
                    "float one call, array the next) changes the "
                    "abstract signature and retraces; wrap with "
                    "jnp.asarray(v, dtype) (the jnp.float32(lr) "
                    "idiom) or mark the argument static"))
    return out


# ----------------------------------------------------------------------
# unbucketed AOT table indexes (X005)
# ----------------------------------------------------------------------
def _routes_through_bucket(expr, bucketed):
    if isinstance(expr, ast.Name):
        return expr.id in bucketed
    if isinstance(expr, ast.Call):
        name = _call_name(expr) or ""
        return bool(_BUCKET_CALL_RE.search(name))
    if isinstance(expr, ast.BoolOp):
        return all(_routes_through_bucket(v, bucketed)
                   for v in expr.values)
    if isinstance(expr, ast.IfExp):
        return _routes_through_bucket(expr.body, bucketed) and \
            _routes_through_bucket(expr.orelse, bucketed)
    return False


def _dynamic_size(expr):
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and _call_name(node) == "len":
            return True
        if isinstance(node, ast.Attribute) and node.attr == "shape":
            return True
    return False


def _bucket_findings(fn, qual):
    # names that went THROUGH bucket routing, and names that carry a
    # raw dynamic size
    bucketed = {a.arg for a in fn.args.args if a.arg == "bucket"}
    dynamic = set()
    for node in _shallow_walk(fn):
        if isinstance(node, ast.Assign):
            names = [n for t in node.targets for n in _target_names(t)]
            if _routes_through_bucket(node.value, bucketed):
                bucketed.update(names)
            elif _dynamic_size(node.value):
                dynamic.update(names)
        elif isinstance(node, ast.For):
            base = _dotted(node.iter if not isinstance(node.iter,
                                                       ast.Call)
                           else node.iter.func) or ""
            if _PROGRAM_TABLE_RE.search(base.rsplit(".", 2)[-2]
                                        if base.count(".") >= 1
                                        and isinstance(node.iter,
                                                       ast.Call)
                                        else base.rsplit(".", 1)[-1]):
                bucketed.update(_target_names(node.target))
    out = []
    for node in _shallow_walk(fn):
        if not isinstance(node, ast.Subscript):
            continue
        base = _dotted(node.value) or ""
        if not _PROGRAM_TABLE_RE.search(base.rsplit(".", 1)[-1]):
            continue
        idx = node.slice
        if _routes_through_bucket(idx, bucketed):
            continue
        raw = _dynamic_size(idx) or any(
            isinstance(n, ast.Name) and n.id in dynamic
            for n in ast.walk(idx))
        if raw:
            out.append((
                "MXL-X005", node.lineno, qual,
                "dynamic size indexes an AOT program table without "
                "bucket routing — every novel size lowers a fresh "
                "program; pick the bucket with buckets.bucket_for / "
                "prefill_bucket first"))
    return out


# ----------------------------------------------------------------------
# donated-buffer reuse (X006)
# ----------------------------------------------------------------------
def _donation_findings(fn, qual):
    donated_fns = {}        # local name -> donate_argnums tuple
    for node in _shallow_walk(fn):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                _call_name(node.value) in _JIT_WRAPPERS:
            nums = _donate_argnums(node.value)
            if nums:
                for t in node.targets:
                    for name in _target_names(t):
                        donated_fns[name] = nums
    if not donated_fns:
        return []
    donations = []          # (var, call_lineno)
    assigns = []            # (var, lineno)
    reads = []              # (var, lineno)
    for node in _shallow_walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                for name in _target_names(t):
                    assigns.append((name, node.lineno))
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id in donated_fns:
            for i in donated_fns[node.func.id]:
                if i < len(node.args) and \
                        isinstance(node.args[i], ast.Name):
                    donations.append((node.args[i].id, node.lineno))
        elif isinstance(node, ast.Name) and \
                isinstance(node.ctx, ast.Load):
            reads.append((node.id, node.lineno))
    out = []
    for var, dline in donations:
        for rvar, rline in reads:
            if rvar != var or rline <= dline:
                continue
            refreshed = any(a == var and dline <= aline <= rline
                            for a, aline in assigns)
            if not refreshed:
                out.append((
                    "MXL-X006", rline, qual,
                    "donated buffer %r read after donation — "
                    "jit(donate_argnums) invalidates the argument "
                    "buffer; use the returned replacement (rebind the "
                    "name from the call result)" % var))
                break
    return out


# ----------------------------------------------------------------------
# driver
# ----------------------------------------------------------------------
def analyze_retrace_paths(paths, root=None):
    """Run MXL-X001..X006 over .py files/dirs.  Returns a list of
    finding dicts: {rule, line, anchor, message[, severity]}."""
    root = root or os.getcwd()
    findings = []
    for path in iter_py_files(paths):
        source, tree = _parse(path)
        rel = os.path.relpath(path, root)
        if source is None:
            findings.append({
                "rule": "MXL-X001", "line": 0,
                "anchor": "%s:<file>" % rel, "severity": "warning",
                "message": "cannot parse %s for the retrace lint: %s"
                           % (rel, tree)})
            continue
        traced = _traced_defs(tree)
        jitted_names = _jitted_local_names(tree)
        raw = []
        seen = set()
        for qual, fn in _functions(tree):
            decs = _decorators(fn)
            if fn in traced or _TRACED_DECORATOR in decs or \
                    decs & _JIT_WRAPPERS:
                static = traced.get(fn, set())
                raw.extend(_traced_scope_findings(fn, qual, static))
            raw.extend(_key_hygiene_findings(fn, qual))
            raw.extend(_per_step_jit_findings(fn, qual))
            raw.extend(_weak_type_findings(fn, qual, jitted_names))
            raw.extend(_bucket_findings(fn, qual))
            raw.extend(_donation_findings(fn, qual))

        suppress = _suppressions(source)
        # def/class lines participate in suppression
        anchor_lines = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                # a marker above the first decorator covers the def too
                head = min([node.lineno]
                           + [d.lineno for d in node.decorator_list])
                for sub in ast.walk(node):
                    ln = getattr(sub, "lineno", None)
                    if ln is not None:
                        anchor_lines.setdefault(ln, set()).update(
                            (node.lineno, head))
        for rule, line, qualname, message in raw:
            if (rule, line, message) in seen:
                continue        # traced nesting can re-visit a stmt
            seen.add((rule, line, message))
            ids = suppress.get(line, set()) | \
                suppress.get(line - 1, set())
            for defline in anchor_lines.get(line, ()):
                ids |= suppress.get(defline, set()) | \
                    suppress.get(defline - 1, set())
            if "all" in ids or rule in ids:
                continue
            findings.append({
                "rule": rule, "line": line,
                "anchor": "%s:%s" % (rel, qualname),
                "message": "%s [in %s]" % (message, qualname)})
    findings.sort(key=lambda f: (f["anchor"], f["line"], f["rule"]))
    return findings


# ----------------------------------------------------------------------
# rule registration
# ----------------------------------------------------------------------
def _source_findings(ctx):
    if "retrace" not in ctx.cache:
        ctx.cache["retrace"] = analyze_retrace_paths(ctx.source_paths)
    return ctx.cache["retrace"]


def _relay(ctx, rule):
    if not ctx.source_paths:
        return
    for f in _source_findings(ctx):
        if f["rule"] == rule:
            ctx.report(None, f["message"],
                       severity=f.get("severity"),
                       anchor=f["anchor"], line=f["line"])


@register_rule("MXL-X001", "error",
               "python control flow on a tensor-derived value inside "
               "a traced scope (per-value retrace)")
def traced_control_flow(ctx):
    """`if`/`while`/host materialization on a tracer inside a traced
    function — each distinct value forces a fresh trace."""
    _relay(ctx, "MXL-X001")


@register_rule("MXL-X002", "error",
               "unstable cache-key ingredient (id(), unsorted "
               "iteration, env read inside a trace)")
def unstable_cache_key(ctx):
    """id()/dict-order/set-order in a compile-cache key, or an
    environment read baked into a traced body."""
    _relay(ctx, "MXL-X002")


@register_rule("MXL-X003", "error",
               "jit/lower constructed on a per-request or per-step "
               "path, bypassing the program registry")
def per_step_jit(ctx):
    """Fresh jax.jit/.lower on a hot path — steady state must perform
    zero lowerings; build once or route through the registry."""
    _relay(ctx, "MXL-X003")


@register_rule("MXL-X004", "warning",
               "bare python scalar passed to a jitted entry point "
               "(weak-type retrace hazard)")
def weak_type_leak(ctx):
    """Python scalar crossing the trace boundary positionally — the
    weak-type abstract signature flaps between call styles."""
    _relay(ctx, "MXL-X004")


@register_rule("MXL-X005", "error",
               "dynamic shape fed to an AOT program table without "
               "bucket routing")
def unbucketed_shape(ctx):
    """len()/shape-derived index into _prefill/_decode/predictors —
    serving must route through the planner's buckets."""
    _relay(ctx, "MXL-X005")


@register_rule("MXL-X006", "error",
               "donated buffer reused after donation")
def donated_reuse(ctx):
    """A buffer passed at a donate_argnums position read again after
    the call instead of its returned replacement."""
    _relay(ctx, "MXL-X006")
