"""Graph-level distributed-correctness lint (MXL-D001..D003).

The whole point of binding the symbolic graph to ONE XLA computation
(executor.py) is that every rank runs the identical SPMD program —
collectives pair up across the pod by program position, nothing else.
The moment two ranks issue different collective sequences (different
order, different shapes/axes, or a collective one rank skips) the pod
deadlocks: XLA/ICI rendezvous have no timeouts by default and no way
to re-match out-of-order participants.

This pass simulates the collective trace each rank would issue and
diffs the traces, chip-free:

- the implicit collectives come from the MXL-P sharding propagation
  (``propagation.propagate`` events: psum/allgather/reshard with axes
  and per-device bytes — already in topo order);
- explicit collectives are declared with the ``__collective__`` node
  attr (``"barrier"``, ``"allreduce:dp"``) — the graph-side mirror of
  the runtime seams kvstore marks with ``@collective_seam``;
- rank-conditional execution is declared with the ``__rank_cond__``
  node attr, a small conjunctive grammar (``coordinator``,
  ``noncoordinator``, ``rank==N``, ``rank!=N``, ``rank<N``,
  ``rank<=N``, ``rank>N``, ``rank>=N``, ``rank%K==J``; several
  AND-ed with ``;``), inherited by every downstream node — a consumer
  of a coordinator-only tensor only runs on the coordinator.

Rules (all gated on ``ctx.world_size > 1`` — set ``world_size=`` on
:func:`analyze`/``Symbol.validate``, pass ``--distributed
--world-size N`` to mxlint, or export ``MXTPU_LINT_DISTRIBUTED=1``):

- **MXL-D001** (error) — positional order/kind mismatch between two
  ranks' traces;
- **MXL-D002** (error) — collectives pair up by position but disagree
  on signature (axes / payload bytes / shape);
- **MXL-D003** (error) — a collective issued on a strict subset of
  ranks: the static form of the deadlock every barrier bug in PR 3
  produced at runtime.  Unparseable ``__rank_cond__`` specs are also
  reported here (warning severity) and treated as always-true so one
  typo doesn't hide real findings.
"""
from __future__ import annotations

from .core import register_rule
from .propagation import propagate, edge_shapes, edge_types, fmt_bytes

__all__ = ["RANK_COND_ATTR", "COLLECTIVE_ATTR", "parse_rank_cond",
           "node_rank_conds", "collective_trace"]

RANK_COND_ATTR = "__rank_cond__"
COLLECTIVE_ATTR = "__collective__"

# kinds the propagation events carry -> the collective each lowers to
_KIND_NAMES = {"reduce": "allreduce", "gather": "allgather",
               "reshard": "alltoall", "alltoall": "alltoall"}


# ----------------------------------------------------------------------
# __rank_cond__ grammar
# ----------------------------------------------------------------------
def _parse_one(term):
    """One predicate -> callable(rank) -> bool.  Raises ValueError."""
    t = term.strip().replace(" ", "")
    if not t:
        raise ValueError("empty rank condition")
    if t == "coordinator":
        return lambda r: r == 0
    if t == "noncoordinator":
        return lambda r: r != 0
    if t.startswith("rank%"):
        rest = t[len("rank%"):]
        if "==" not in rest:
            raise ValueError("modulo condition needs '==': %r" % term)
        k_s, j_s = rest.split("==", 1)
        k, j = int(k_s), int(j_s)
        if k <= 0:
            raise ValueError("modulo base must be positive: %r" % term)
        return lambda r, k=k, j=j: r % k == j
    for op, fn in (("==", lambda r, n: r == n),
                   ("!=", lambda r, n: r != n),
                   ("<=", lambda r, n: r <= n),
                   (">=", lambda r, n: r >= n),
                   ("<", lambda r, n: r < n),
                   (">", lambda r, n: r > n)):
        if t.startswith("rank" + op):
            n = int(t[len("rank" + op):])
            return lambda r, fn=fn, n=n: fn(r, n)
    raise ValueError("cannot parse rank condition %r" % term)


def parse_rank_cond(spec):
    """``__rank_cond__`` string -> list of predicates (AND-ed).

    Grammar: ``coordinator`` | ``noncoordinator`` | ``rank==N`` |
    ``rank!=N`` | ``rank<N`` | ``rank<=N`` | ``rank>N`` | ``rank>=N``
    | ``rank%K==J``; several terms AND-ed with ``;``.  Raises
    ValueError on any unparseable term.
    """
    return [_parse_one(t) for t in str(spec).split(";") if t.strip()]


def node_rank_conds(ctx):
    """``id(node) -> {cond_spec: origin_node_name}`` with inheritance:
    a node conditioned on ``rank==0`` conditions everything downstream
    of it (its outputs only exist on rank 0).  Bad specs collect into
    ``ctx.cache['rank_cond_errors']`` as ``(node, spec, error)``.
    """
    if "rank_conds" in ctx.cache:
        return ctx.cache["rank_conds"]
    conds = {}
    errors = ctx.cache.setdefault("rank_cond_errors", [])
    for node in ctx.topo:          # already topological: inputs first
        eff = {}
        for inp, _idx in (node.inputs or ()):
            eff.update(conds.get(id(inp), {}))
        own = (node.attrs or {}).get(RANK_COND_ATTR)
        if own:
            try:
                parse_rank_cond(own)
            except ValueError as exc:
                errors.append((node, own, str(exc)))
            else:
                for term in str(own).split(";"):
                    if term.strip():
                        eff.setdefault(term.strip(), node.name)
        conds[id(node)] = eff
    ctx.cache["rank_conds"] = conds
    return conds


def _present_ranks(cond_map, world):
    """Ranks (of ``range(world)``) satisfying every condition."""
    preds = []
    for spec in cond_map:
        try:
            preds.extend(parse_rank_cond(spec))
        except ValueError:
            continue               # reported separately; treat as true
    return frozenset(r for r in range(world)
                     if all(p(r) for p in preds))


# ----------------------------------------------------------------------
# the trace
# ----------------------------------------------------------------------
def collective_trace(ctx):
    """Ordered collectives the bound program will issue, as dicts
    ``{"node", "name", "kind", "sig", "conds", "detail"}``.

    Merges the MXL-P propagation events (implicit collectives XLA
    inserts for the sharding solution) with explicit ``__collective__``
    nodes, in topo-position order; ``sig`` is the cross-rank match
    signature (kind + axes + payload), ``conds`` the inherited
    ``__rank_cond__`` map.
    """
    if "collective_trace" in ctx.cache:
        return ctx.cache["collective_trace"]
    conds = node_rank_conds(ctx)
    order = {id(n): i for i, n in enumerate(ctx.topo)}
    shapes = edge_shapes(ctx)
    types = edge_types(ctx)
    entries = []                   # (topo_idx, sub_order, entry)

    for sub, ev in enumerate(propagate(ctx)["events"]):
        if ev["kind"] not in _KIND_NAMES:
            continue               # degradation notes, not collectives
        node = ev["node"]
        kind = _KIND_NAMES[ev["kind"]]
        axes = tuple(ev.get("axes") or ())
        entry = {
            "node": node, "name": getattr(node, "name", str(node)),
            "kind": kind, "sig": (kind, axes, ev.get("bytes") or 0),
            "conds": conds.get(id(node), {}),
            "detail": "%s over %s (~%s per device)"
                      % (kind, "+".join(axes) or "?",
                         fmt_bytes(ev.get("bytes") or 0)),
        }
        entries.append((order.get(id(node), len(order)), sub, entry))

    for node in ctx.topo:
        spec = (node.attrs or {}).get(COLLECTIVE_ATTR)
        if not spec:
            continue
        kind, _, axes_s = str(spec).partition(":")
        kind = kind.strip() or "barrier"
        axes = tuple(a.strip() for a in axes_s.split(",") if a.strip())
        shape = shapes.get((id(node), 0))
        dtype = types.get((id(node), 0))
        entry = {
            "node": node, "name": node.name, "kind": kind,
            "sig": (kind, axes, shape, str(dtype) if dtype else None),
            "conds": conds.get(id(node), {}),
            "detail": "%s%s at node %s"
                      % (kind, " over " + "+".join(axes) if axes else "",
                         node.name),
        }
        entries.append((order.get(id(node), len(order)), -1, entry))

    entries.sort(key=lambda t: (t[0], t[1]))
    trace = [e for _i, _s, e in entries]
    ctx.cache["collective_trace"] = trace
    return trace


# ----------------------------------------------------------------------
# the per-rank simulation shared by D001..D003
# ----------------------------------------------------------------------
def _ctx_group(node):
    g = (getattr(node, "attrs", None) or {}).get("ctx_group")
    return " [ctx_group=%s]" % g if g else ""


def _simulate(ctx):
    """Diff the per-rank traces; returns findings ``(rule, node,
    message)`` cached in ``ctx.cache['distributed']``."""
    if "distributed" in ctx.cache:
        return ctx.cache["distributed"]
    findings = []
    ctx.cache["distributed"] = findings
    world = ctx.world_size or 0
    if world <= 1 or ctx.symbol is None:
        return findings

    trace = collective_trace(ctx)
    for node, spec, err in ctx.cache.get("rank_cond_errors", ()):
        findings.append((
            "MXL-D003", node, "warning",
            "unparseable %s=%r (%s): treating the node as running on "
            "every rank, which may hide a real divergence"
            % (RANK_COND_ATTR, spec, err)))
    if not trace:
        return findings

    present = [_present_ranks(ev["conds"], world) for ev in trace]
    full = frozenset(range(world))
    if all(p == full for p in present):
        return findings

    lengths = {r: sum(1 for p in present if r in p) for r in full}
    if len(set(lengths.values())) > 1:
        # some rank issues fewer collectives: every partially-present
        # event is a rendezvous a subset of the pod never joins
        seen = set()
        for ev, p in zip(trace, present):
            if p == full or ev["name"] in seen:
                continue
            seen.add(ev["name"])
            origin = ", ".join(sorted(
                "%s (from node %s)" % (c, o)
                for c, o in ev["conds"].items())) or "none"
            if p:
                who = "only rank%s %s of %d" % (
                    "" if len(p) == 1 else "s",
                    ",".join(str(r) for r in sorted(p)), world)
            else:
                who = "NO rank at world size %d" % world
            findings.append((
                "MXL-D003", ev["node"], None,
                "collective %s%s is issued on %s (%s: %s): the "
                "remaining ranks never join the rendezvous and the "
                "pod deadlocks — hoist the collective out of the "
                "rank-conditional region or run it on every rank"
                % (ev["detail"], _ctx_group(ev["node"]), who,
                   RANK_COND_ATTR, origin)))
        return findings

    # equal counts: pair traces positionally against rank 0 and diff
    per_rank = {r: [ev for ev, p in zip(trace, present) if r in p]
                for r in full}
    ref = per_rank[0]
    seen = set()                   # one finding per program position
    for r in sorted(full - {0}):
        for pos, (a, b) in enumerate(zip(ref, per_rank[r])):
            if a is b or pos in seen:
                continue
            seen.add(pos)
            if a["kind"] != b["kind"]:
                findings.append((
                    "MXL-D001", a["node"], None,
                    "collective order diverges across ranks: at "
                    "position %d rank 0 issues %s%s while rank %d "
                    "issues %s%s — XLA pairs collectives by program "
                    "position, so the pod deadlocks (or silently "
                    "mixes payloads)"
                    % (pos, a["detail"], _ctx_group(a["node"]), r,
                       b["detail"], _ctx_group(b["node"]))))
                break
            findings.append((
                "MXL-D002", a["node"], None,
                "collective signature diverges across ranks: at "
                "position %d rank 0 issues %s but rank %d issues %s "
                "— mismatched axes/payload in one rendezvous is "
                "undefined behavior on ICI"
                % (pos, a["detail"], r, b["detail"])))
            break
    return findings


def _report(ctx, rule):
    for rid, node, severity, message in _simulate(ctx):
        if rid == rule:
            ctx.report(node, message, severity=severity)


@register_rule("MXL-D001", "error",
               "collective order mismatch across ranks")
def collective_order_mismatch(ctx):
    """Two ranks issue different collective sequences: deadlock."""
    _report(ctx, "MXL-D001")


@register_rule("MXL-D002", "error",
               "collective signature mismatch across ranks")
def collective_signature_mismatch(ctx):
    """Collectives pair by position but disagree on axes/payload."""
    _report(ctx, "MXL-D002")


@register_rule("MXL-D003", "error",
               "collective under rank-conditional control flow")
def collective_rank_conditional(ctx):
    """A collective a strict subset of ranks issues: static deadlock."""
    _report(ctx, "MXL-D003")
