"""Static Mosaic tile-rule validation for Pallas kernel specs (MXL-K).

The round-5 AOT audit proved the most expensive class of bug in this
repo is statically detectable: the ring-attention flash kernel's lse
output block was a 1-D ``(block_q,)`` stats row — Mosaic requires the
last two block dims to tile to the dtype's minimum tile, so the kernel
had never compiled for a real TPU, and nothing said so until a chip
window was spent discovering it.  This pass re-derives Mosaic's layout
rules from the Pallas guide and applies them to a *declared* description
of every kernel's BlockSpecs, with zero chip time and zero compiler
invocations:

- minimum tile by dtype on the last two (sublane, lane) dims of each
  block: (8, 128) float32, (16, 128) bfloat16, (32, 128) int8/fp8 —
  a partial tiling must be a multiple of the granule; a block covering
  the whole array dim is legal at any size (Mosaic pads it);
- a block must have at least two non-squeezed dims (the lse bug: a 1-D
  stats row cannot be a TPU output block — broadcast it across a
  128-lane dim instead);
- the lane (last) dim of a partial tiling must be a multiple of 128;
- grid divisibility: an array dim not divisible by its block dim makes
  the trailing grid step compute padding (warning, not error — Mosaic
  masks it, you just pay for dead lanes);
- containment: a block dim may not exceed its array dim.

Kernels declare themselves through :func:`register_kernel_spec` — the
module defining the ``pallas_call`` registers a provider returning one
or more spec dicts built from the SAME shape arithmetic the call uses
(see ``parallel/ring_attention.flash_kernel_spec``), so every BlockSpec
in the repo is checked on each ``Symbol.validate()`` / ``mxlint`` run.
``rtc.Rtc`` checks its whole-array blocks at build time through
:func:`block_findings` (knob: ``MXTPU_RTC_LINT``).

A spec dict::

    {"name": "flash_forward",
     "origin": "mxnet_tpu/parallel/ring_attention.py",
     "grid": (8, 4),
     "blocks": [{"role": "in", "name": "q",
                 "block": (None, 128, 64),     # None = squeezed dim
                 "array": (8, 512, 64),
                 "dtype": "float32"}, ...]}
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as _np

from .core import register_rule

__all__ = ["LANES", "min_tile", "KERNEL_SPECS", "register_kernel_spec",
           "unregister_kernel_spec", "block_findings", "spec_findings",
           "kernel_spec_issues"]

LANES = 128
# itemsize -> minimum sublane count (packing: narrower types stack more
# rows into one 32-bit-deep vreg sublane)
_MIN_SUBLANES = {8: 4, 4: 8, 2: 16, 1: 32}


def min_tile(dtype):
    """Mosaic minimum tile (sublanes, lanes) for ``dtype``."""
    itemsize = _np.dtype(dtype or _np.float32).itemsize
    return (_MIN_SUBLANES.get(itemsize, 8), LANES)


# ----------------------------------------------------------------------
# kernel spec registry
# ----------------------------------------------------------------------
KERNEL_SPECS = OrderedDict()    # name -> provider() -> spec dict | [dict]


def register_kernel_spec(name, provider):
    """Register a Pallas kernel's block layout for static validation.

    ``provider`` is a zero-arg callable returning a spec dict (or list
    of them) — lazy so registration at import time stays free — or the
    spec itself.  Re-registering a name overwrites (idempotent module
    re-import)."""
    if not callable(provider):
        spec = provider
        provider = lambda: spec     # noqa: E731
    KERNEL_SPECS[name] = provider
    return provider


def unregister_kernel_spec(name):
    KERNEL_SPECS.pop(name, None)


def _ensure_builtin_specs():
    """Import the modules that define in-tree Pallas kernels so their
    registrations exist even when the caller never touched them."""
    try:
        from ..parallel import ring_attention  # noqa: F401
    except Exception:
        pass
    try:
        from .. import kernels  # noqa: F401  (quantize/flash_decode/fused_opt)
    except Exception:
        pass


# ----------------------------------------------------------------------
# block validation
# ----------------------------------------------------------------------
def block_findings(block, array, dtype, label="block"):
    """Validate one block against one array; returns a list of
    ``(rule_id, severity, message)``.

    ``block`` entries are ints or None (squeezed dims, pallas
    ``BlockSpec((None, bq, d), ...)`` style); ``block=None`` means the
    whole array is one block (the rtc path)."""
    findings = []
    array = tuple(int(d) for d in array)
    if block is None:
        block = array
    block = tuple(block)
    if len(block) != len(array):
        findings.append((
            "MXL-K004", "error",
            "%s: block rank %d != array rank %d"
            % (label, len(block), len(array))))
        return findings
    # containment + the positions of the non-squeezed dims
    kept = []               # (array_dim_index, block_extent)
    for i, b in enumerate(block):
        if b is None:
            continue
        b = int(b)
        if b > array[i]:
            findings.append((
                "MXL-K004", "error",
                "%s: block dim %d (%d) exceeds array dim (%d)"
                % (label, i, b, array[i])))
        elif array[i] % b:
            pad_steps = -array[i] % b
            findings.append((
                "MXL-K003", "warning",
                "%s: array dim %d (%d) is not divisible by block (%d): "
                "the trailing grid step computes %d padded rows"
                % (label, i, array[i], b, pad_steps)))
        kept.append((i, b))
    if len(kept) < 2:
        findings.append((
            "MXL-K001", "error",
            "%s: block has %d tileable dim(s) after squeezing — Mosaic "
            "tiles the last two dims to (sublane, %d) and a %d-D block "
            "cannot be laid out; broadcast stats across a %d-lane dim "
            "instead (the historical flash-lse bug)"
            % (label, len(kept), LANES, len(kept), LANES)))
        return findings
    sub_need, lane_need = min_tile(dtype)
    (lane_i, lane_b) = kept[-1]
    (sub_i, sub_b) = kept[-2]
    # a block covering its whole array dim is legal at any size (Mosaic
    # pads the tail tile); a PARTIAL tiling must align to the granule
    if lane_b != array[lane_i] and lane_b % lane_need:
        findings.append((
            "MXL-K002", "error",
            "%s: lane (last) block dim %d is neither the full array dim "
            "(%d) nor a multiple of %d — Mosaic cannot window the lane "
            "axis off-granule" % (label, lane_b, array[lane_i], lane_need)))
    if sub_b != array[sub_i] and sub_b % sub_need:
        findings.append((
            "MXL-K001", "error",
            "%s: sublane block dim %d is neither the full array dim (%d) "
            "nor a multiple of the %s minimum tile (%d, %d)"
            % (label, sub_b, array[sub_i],
               _np.dtype(dtype or _np.float32).name, sub_need, lane_need)))
    return findings


def spec_findings(spec):
    """Validate one kernel spec dict; ``(rule_id, severity, message)``
    list, each message prefixed with the kernel name."""
    findings = []
    name = spec.get("name", "<kernel>")
    grid = spec.get("grid")
    if grid is not None and any(int(g) <= 0 for g in grid):
        findings.append(("MXL-K003", "warning",
                         "kernel %s: grid %s has a non-positive extent"
                         % (name, tuple(grid))))
    for blk in spec.get("blocks", ()):
        label = "kernel %s, %s block %r" % (
            name, blk.get("role", "in"), blk.get("name", "?"))
        findings.extend(block_findings(blk.get("block"), blk["array"],
                                       blk.get("dtype"), label=label))
    return findings


def kernel_spec_issues():
    """Validate every registered kernel spec.

    Returns ``[(kernel_name, rule_id, severity, message)]``; a provider
    that raises contributes one MXL-K004 error (a spec that cannot even
    be built is a broken registration, not a pass)."""
    _ensure_builtin_specs()
    out = []
    for name, provider in KERNEL_SPECS.items():
        try:
            specs = provider()
        except Exception as exc:  # noqa: BLE001
            out.append((name, "MXL-K004", "error",
                        "kernel spec provider %r failed: %s" % (name, exc)))
            continue
        if isinstance(specs, dict):
            specs = [specs]
        for spec in specs:
            for rule_id, sev, msg in spec_findings(spec):
                out.append((name, rule_id, sev, msg))
    return out


# ----------------------------------------------------------------------
# the MXL-K rules
# ----------------------------------------------------------------------
def _findings_by_rule(ctx):
    if "kernel_findings" not in ctx.cache:
        by_rule = {}
        if ctx.target == "tpu":
            for _name, rule_id, sev, msg in kernel_spec_issues():
                by_rule.setdefault(rule_id, []).append((sev, msg))
        ctx.cache["kernel_findings"] = by_rule
    return ctx.cache["kernel_findings"]


def _report_rule(ctx, rule_id):
    for sev, msg in _findings_by_rule(ctx).get(rule_id, ()):
        ctx.report(None, msg, severity=sev, rule_id=rule_id)


@register_rule("MXL-K001", "error",
               doc="pallas block violates the Mosaic dtype minimum tile")
def _rule_k001(ctx):
    _report_rule(ctx, "MXL-K001")


@register_rule("MXL-K002", "error",
               doc="pallas block lane dim not 128-aligned")
def _rule_k002(ctx):
    _report_rule(ctx, "MXL-K002")


@register_rule("MXL-K003", "warning",
               doc="pallas grid padding: array dim not divisible by block")
def _rule_k003(ctx):
    _report_rule(ctx, "MXL-K003")


@register_rule("MXL-K004", "error",
               doc="pallas block exceeds its array (or spec is malformed)")
def _rule_k004(ctx):
    _report_rule(ctx, "MXL-K004")
