"""Collective-placement audit (rule family MXL-C).

The reference split gradient reduction between two machines: device-side
trees (comm.h) for ``device``/``local`` kvstores and ps-lite RPC for
``dist_*``.  Here every reduction is an XLA collective over mesh axes,
so the *scope* of each collective is statically visible — and three
classic deployment mistakes become lintable:

- MXL-C001  kvstore scope vs mesh scope: an unknown kvstore type
            (error), a device-scope kvstore under a mesh larger than one
            process can hold (error — its reduction would silently
            cover only local devices), or ``dist_async`` (warning — jax
            collectives are synchronous; it runs with dist_sync
            semantics, the documented divergence);
- MXL-C002  a collective crossing a pipeline-stage boundary: a
            reduce/gather lands on a node whose inputs live in a
            different ``ctx_group`` stage — the transfer serializes the
            pipeline (only audited when the graph actually uses >= 2
            groups);
- MXL-C003  a tp-sharded matmul without its matching reduction: the
            propagation pass marked a one-sided sharded contraction
            (``matmul_gather``) or a head-parallel attention whose out
            projection doesn't close the psum (``attn_unreduced``) —
            XLA falls back to all-gathering activations, usually 2x the
            ICI traffic of the intended row-parallel psum.
"""
from __future__ import annotations

from .core import register_rule
from .propagation import propagate

_SCOPED_KINDS = ("reduce", "gather", "reshard")


@register_rule("MXL-C001", "error",
               "kvstore scope does not match the mesh scope")
def kvstore_scope(ctx):
    """Gradient-reduction scope vs where the gradients actually live."""
    kv = ctx.kvstore
    if kv is None:
        return
    from ..kvstore import _VALID_TYPES
    base = str(kv).lower()
    if base not in _VALID_TYPES:
        ctx.report(None, "unknown kvstore type %r (valid: %s)"
                   % (kv, ", ".join(_VALID_TYPES)))
        return
    if ctx.mesh is not None and not base.startswith("dist"):
        import jax
        try:
            local = jax.local_device_count()
        except Exception:
            local = None
        mesh_size = getattr(ctx.mesh, "size", None)
        if local and mesh_size and mesh_size > local:
            ctx.report(None,
                       "kvstore %r reduces across this process's devices "
                       "only, but the mesh spans %d devices (> %d local): "
                       "gradients would silently cover one process — use a "
                       "dist_sync kvstore" % (kv, mesh_size, local))
    if base.startswith("dist_async"):
        ctx.report(None, "kvstore %r: jax collectives are synchronous, so "
                   "async runs with dist_sync semantics (documented "
                   "divergence) — updates are NOT applied eagerly per "
                   "worker" % kv, severity="warning")


@register_rule("MXL-C002", "error",
               "collective crosses a pipeline-stage boundary")
def collective_across_stage(ctx):
    """A psum/all-gather whose operand lives in another ctx_group stage
    serializes the pipeline: the collective cannot start until the
    upstream stage finishes its transfer."""
    if ctx.mesh is None:
        return
    groups = {n.attrs.get("ctx_group") for n in ctx.op_nodes()
              if n.attrs.get("ctx_group")}
    if len(groups) < 2:
        return
    for ev in propagate(ctx)["events"]:
        if ev["kind"] not in _SCOPED_KINDS:
            continue
        node = ev["node"]
        here = node.attrs.get("ctx_group")
        for c, _ci in node.inputs:
            there = c.attrs.get("ctx_group")
            if there and there != here:
                ctx.report(node,
                           "%s over %s at %r sits on stage %r but consumes "
                           "%r from stage %r: the collective crosses a "
                           "pipeline boundary and serializes both stages — "
                           "keep reductions inside one stage" % (
                               ev["kind"], "+".join(ev["axes"]), node.name,
                               here or "<default>", c.name, there))
                break


@register_rule("MXL-C003", "warning",
               "tp-sharded matmul without its matching reduction")
def unmatched_reduction(ctx):
    """One-sided sharded contractions: the layout implies a psum the
    graph never sets up, so XLA gathers activations instead."""
    if ctx.mesh is None:
        return
    for ev in propagate(ctx)["events"]:
        if ev["kind"] in ("matmul_gather", "attn_unreduced"):
            ctx.report(ev["node"], ev["message"])
