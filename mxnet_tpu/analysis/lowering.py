"""TPU-lowering lint passes (rule family MXL-L).

The graph executes as ONE traced XLA computation; anything XLA cannot
stage on-device either fails at trace time or quietly wrecks the fused
step.  These passes read the op registry's lowering metadata
(``OperatorProperty.host_callback`` / ``unsupported_platforms``), the
executor's own mirror-segment partition, and the parallel layer's
sharding rules:

- MXL-L001  op with no JAX lowering for the target platform (abstract
            ``forward`` or an explicit ``unsupported_platforms`` entry)
            — error;
- MXL-L002  host-callback op inside a mirrored (``jax.checkpoint``)
            segment: the callback re-fires on backward recompute, so
            side effects double and the recompute stalls on host
            round-trips — error;
- MXL-L003  host-callback op anywhere in the graph: XLA cannot fuse or
            shard across it (the reference's Custom ops broke bulk
            segments the same way, graph_executor.cc:860-875) — info;
- MXL-L004  sharding rule produces a PartitionSpec referencing mesh axes
            the bound mesh doesn't have (error) or partitioning a
            non-divisible dimension (warning).
"""
from __future__ import annotations

from .core import register_rule


def _op_kind(node):
    return type(node.op).op_name or type(node.op).__name__


@register_rule("MXL-L001", "error", "op has no JAX lowering for target")
def no_lowering(ctx):
    """Ops that cannot lower for the target platform at all."""
    from ..ops.registry import OperatorProperty
    for node in ctx.op_nodes():
        cls = type(node.op)
        if cls.forward is OperatorProperty.forward:
            ctx.report(node, "op %s has no JAX lowering (abstract "
                       "forward): tracing will raise NotImplementedError"
                       % _op_kind(node))
        elif ctx.target in getattr(node.op, "unsupported_platforms", ()):
            ctx.report(node, "op %s declares no lowering for platform "
                       "%r" % (_op_kind(node), ctx.target))


def _mirrored_nodes(ctx):
    """Nodes the executor would place inside jax.checkpoint segments,
    via the executor's OWN partitioner (no second mirror-rule copy to
    drift)."""
    from ..executor import _mirror_segments
    out = []
    for is_mirror, nodes in _mirror_segments(ctx.op_nodes()):
        if is_mirror:
            out.extend(nodes)
    return out


@register_rule("MXL-L002", "error",
               "host callback inside a mirrored segment")
def callback_in_mirror(ctx):
    """pure_callback under jax.checkpoint re-fires on backward
    recompute: side effects double, and every recompute stalls on a
    host round-trip."""
    for node in _mirrored_nodes(ctx):
        if getattr(node.op, "host_callback", False):
            ctx.report(node, "op %s runs a host callback but is inside "
                       "a mirrored (jax.checkpoint) segment: the "
                       "callback fires again on backward recompute — "
                       "drop force_mirroring/MXNET_BACKWARD_DO_MIRROR "
                       "for this node" % _op_kind(node))


@register_rule("MXL-L003", "info", "host-callback op breaks fusion")
def host_callback_present(ctx):
    """Host callbacks split the fused computation and serialize on
    device->host->device transfers every step."""
    for node in ctx.op_nodes():
        if getattr(node.op, "host_callback", False):
            ctx.report(node, "op %s executes via a host python callback: "
                       "XLA cannot fuse or shard across it"
                       % _op_kind(node))


@register_rule("MXL-L004", "error",
               "sharding spec references axes missing from the mesh")
def sharding_axes(ctx):
    """Explicit ShardingRules evaluated against the bound mesh."""
    if ctx.mesh is None or ctx.sharding_rules is None:
        return
    try:
        arg_shapes, _outs, _aux = \
            ctx.symbol.infer_shape_partial(**ctx.shapes)
    except Exception:   # noqa: BLE001 — shape issues are MXL-S002's job
        return
    named = {n: s for n, s in zip(ctx.symbol.list_arguments(), arg_shapes)
             if s is not None}
    for name, spec, problem, fatal in ctx.sharding_rules.validate(
            ctx.mesh, named):
        ctx.report(name, "sharding rule for %r yields %s: %s"
                   % (name, spec, problem),
                   severity="error" if fatal else "warning")
