"""Chip-free autotuner: search-space grammar, constraint pruning,
memoized static pricing, and replay-manifest construction
(docs/perf.md "Autotuning & chip windows").

Chip windows are scarce, so config selection happens off-chip: every
model the search needs already exists in this package and prices a
graph without lowering anything — MXL-R (roofline MFU ceiling,
calibrated against the compiled AOT table in AOT_r05.json), MXL-M
(peak-HBM fit), MXL-K (Mosaic tile legality), MXL-E (pipeline/MoE
schedule lint — infeasible stage splits and expert counts are pruned,
a feasible pipeline config's ceiling is scaled by its simulated 1F1B
bubble) and MXL-D (distributed lint).  The tuner enumerates a config
grammar, **prunes infeasible
candidates before pricing them** (an illegal tile or an OOM config
must not spend analysis time, and must never reach a chip), prices the
survivors through one memoized analysis context per distinct graph
(a multi-hundred-config sweep re-lowers each distinct symbol once —
``GraphMemo.stats`` counts it), and ranks by static MFU ceiling with
HBM-headroom tiebreak plus a Pareto frontier over predicted
throughput vs. predicted peak memory.

The output is a deterministic, provenance-stamped **replay manifest**
(``build_manifest``): the ordered top-K configs with predicted
MFU / peak-HBM / ICI bytes and the exact ``bench.py`` command line for
each, so a chip window runs only the top-K in order.  Identical inputs
produce byte-identical manifests — nothing time- or machine-dependent
enters the hashed body.  ``tools/autotune.py`` is the CLI; its
``--replay`` side stamps each BENCH line with the manifest config id,
gates every result through the slo.py sentry, and re-ranks the
remaining candidates with :func:`fit_correction` as measured numbers
arrive.

HBM feasibility is a *predictor*, not the MXL-M lint: the analytic
peak keeps every residual live, while the compiled step re-materializes
and dies long before that bound (AOT_r05.json: 11.2 GB compiled temp
at b512 vs 70 GB analytic).  The predictor credits activations with
``MXTPU_AUTOTUNE_ACT_CREDIT`` (default 0.2, calibrated against the
same AOT rows) and shards state across the config's mesh; MXL-M's own
lint semantics are untouched.
"""
from __future__ import annotations

import hashlib
import itertools
import json
import re as _re

from .core import AnalysisContext, run_rules
from .memory import hbm_capacity_bytes, peak_hbm_report
from .propagation import comm_report
from .roofline import (_env_float, _op_costs, device_peaks,
                       roofline_report)
from .tiling import LANES, block_findings

__all__ = ["AXES", "default_space", "parse_space", "space_configs",
           "parse_sharding", "config_id", "canonical_json", "GraphMemo",
           "predicted_peak_hbm", "prune_config", "price_config",
           "search", "build_manifest", "bench_command",
           "fit_correction", "apply_correction", "rerank"]

# ---------------------------------------------------------------------
# search-space grammar
# ---------------------------------------------------------------------
#: axis order IS the grammar order: config dicts, manifest rows and
#: config ids all serialize axes in this order
AXES = ("batch", "remat", "sharding", "dtype", "bucket_mb", "prefetch",
        "serve_block", "serve_buckets", "stages", "microbatches",
        "experts", "capacity_factor")

#: axes whose values are ints ("none" -> None for the optional ones)
_INT_AXES = ("batch", "bucket_mb", "prefetch", "serve_block", "stages",
             "microbatches", "experts")
#: axes whose values are floats
_FLOAT_AXES = ("capacity_factor",)
_OPTIONAL_AXES = ("serve_block", "serve_buckets", "stages", "experts",
                  "capacity_factor")

#: the serve paged-KV pool the MXL-K gate checks serve_block against —
#: (pool_rows, head_dim): any realistic pool dominates the block, so
#: only the block's own granule alignment matters
_SERVE_POOL = (4096, LANES)


def default_space(model="resnet50"):
    """The stock search space: the known-good batch ladder (the
    docs/mfu_gap.md v5e table), both remat policies, single-chip dp,
    bf16 compute, and the PR-8 overlap knob defaults."""
    del model  # one stock space today; per-model spaces can fork here
    return {
        "batch": (64, 128, 256, 512),
        "remat": ("none", "blocks"),
        "sharding": ("dp1",),
        "dtype": ("bfloat16",),
        "bucket_mb": (25,),
        "prefetch": (2,),
        "serve_block": (None,),
        "serve_buckets": (None,),
        # pipeline / MoE axes (MXL-E): single-valued defaults keep the
        # stock sweep's graph count unchanged; widen them with e.g.
        # "stages=2,4;microbatches=4,8" or "experts=4,8"
        "stages": (None,),
        "microbatches": (8,),
        "experts": (None,),
        "capacity_factor": (None,),
    }


def parse_space(spec, base=None):
    """Parse the grammar string ``"batch=64,128;remat=none,blocks;
    sharding=dp1,dp2tp2;dtype=bfloat16,int8;serve_block=16,32"`` into a
    space dict.  Unknown axes are an error; unnamed axes keep their
    ``base`` (default-space) values."""
    space = dict(base or default_space())
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError("bad space term %r (want axis=v1,v2,...)"
                             % part)
        axis, _, raw = part.partition("=")
        axis = axis.strip()
        if axis not in AXES:
            raise ValueError("unknown axis %r (valid: %s)"
                             % (axis, ", ".join(AXES)))
        vals = []
        for tok in raw.split(","):
            tok = tok.strip()
            if not tok:
                continue
            if axis in _OPTIONAL_AXES and tok.lower() == "none":
                vals.append(None)
            elif axis in _INT_AXES:
                vals.append(int(tok))
            elif axis in _FLOAT_AXES:
                vals.append(float(tok))
            else:
                vals.append(tok)
        if not vals:
            raise ValueError("axis %r has no values" % axis)
        space[axis] = tuple(vals)
    return space


def space_configs(space):
    """Enumerate the space as config dicts, in deterministic grammar
    order (itertools.product over AXES)."""
    axes = [tuple(space.get(a) or (default_space()[a])) for a in AXES]
    return [dict(zip(AXES, combo)) for combo in itertools.product(*axes)]


# the "dp2tp2pp4ep2"-style sharding grammar lives with the sharding
# rules it configures; the tuner re-exports it (axes: dp/fsdp, tp, pp
# pipeline stages, ep expert parallelism)
from ..parallel.sharding import _SHARDING_RE, parse_sharding  # noqa: E402,F401


def canonical_json(obj):
    """The one serialization determinism hangs on: sorted keys, no
    whitespace."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def config_id(config):
    """Content-hash id for a config: same config -> same id, on any
    machine, forever (the manifest/BENCH join key)."""
    ordered = {k: config.get(k) for k in AXES}
    ordered["model"] = config.get("model")
    digest = hashlib.sha256(
        canonical_json(ordered).encode()).hexdigest()
    return "at-" + digest[:10]


# ---------------------------------------------------------------------
# models the tuner knows how to build
# ---------------------------------------------------------------------
_RESNET_RE = _re.compile(r"^resnet(\d+)$")


def _model_builder(model):
    """(build_fn(remat_blocks, experts, capacity_factor) -> symbol,
    shapes_fn(batch) -> dict).  ``experts`` / ``capacity_factor`` are
    the MoE axes: on the transformer builders they swap every FFN for a
    routed expert block (ops/moe.py); the conv models reject them."""
    m = _RESNET_RE.match(model)
    if m:
        layers = int(m.group(1))

        def build(remat, experts=None, capacity_factor=None):
            if experts:
                raise ValueError("model %r has no MoE variant (axis "
                                 "experts=%s)" % (model, experts))
            from ..models import resnet
            return resnet.get_symbol(num_classes=1000, num_layers=layers,
                                     mirror_blocks=remat)

        return build, lambda b: {"data": (b, 3, 224, 224)}
    if model in ("transformer", "transformer_moe"):
        def build(remat, experts=None, capacity_factor=None):
            from ..models import transformer, transformer_moe
            if model == "transformer_moe":
                kw = {}
                if experts:
                    kw["num_experts"] = int(experts)
                if capacity_factor:
                    kw["moe_capacity_factor"] = float(capacity_factor)
                return transformer_moe.get_symbol(mirror_blocks=remat,
                                                  **kw)
            if experts:
                return transformer.get_symbol(
                    mirror_blocks=remat, num_experts=int(experts),
                    moe_capacity_factor=float(capacity_factor or 0.0))
            return transformer.get_symbol(mirror_blocks=remat)

        return build, lambda b: {"data": (b, 512)}
    raise ValueError("unknown model %r (resnetNN, transformer or "
                     "transformer_moe)" % (model,))


# ---------------------------------------------------------------------
# memoized per-graph analysis
# ---------------------------------------------------------------------
class GraphMemo(object):
    """One symbol build per distinct (model, remat) and one analysis
    context per distinct *graph* key — configs differing only in
    graph-free axes (bucket_mb, prefetch, serve_buckets, serve_block)
    share every report.  ``stats`` counts re-lowerings so sweeps can
    assert each distinct symbol was analyzed once."""

    def __init__(self, device_kind="v5e", hbm_bytes=None):
        self.device_kind = device_kind
        self.hbm_bytes = hbm_bytes
        self._symbols = {}
        self._ctxs = {}
        self.stats = {"symbols_built": 0, "analyses": 0, "memo_hits": 0}

    def symbol(self, model, remat, experts=None, capacity_factor=None):
        key = (model, remat, experts, capacity_factor)
        if key not in self._symbols:
            build, _shapes = _model_builder(model)
            self._symbols[key] = build(remat == "blocks", experts,
                                       capacity_factor)
            self.stats["symbols_built"] += 1
        return self._symbols[key]

    @staticmethod
    def graph_key(model, config):
        """The axes that change the analyzed graph or its pricing."""
        return (model, config["batch"], config["remat"],
                config["dtype"], config["sharding"],
                config.get("stages"), config.get("microbatches"),
                config.get("experts"), config.get("capacity_factor"))

    def ctx(self, model, config):
        key = self.graph_key(model, config)
        hit = key in self._ctxs
        if hit:
            self.stats["memo_hits"] += 1
            return self._ctxs[key]
        self.stats["analyses"] += 1
        sym = self.symbol(model, config["remat"],
                          config.get("experts"),
                          config.get("capacity_factor"))
        _build, shapes_fn = _model_builder(model)
        deg = parse_sharding(config["sharding"])
        # an explicit "stages" axis pipelines without a pp mesh entry
        # in the sharding rule; both spell the same pipeline degree
        pp = int(config.get("stages") or deg["pp"])
        world = deg["dp"] * deg["tp"] * pp * deg["ep"]
        mesh = None
        if world > 1:
            from ..parallel.mesh import LogicalMesh
            axes = {}
            if deg["dp"] > 1:
                axes["dp"] = deg["dp"]
            if deg["tp"] > 1:
                axes["tp"] = deg["tp"]
            if pp > 1:
                axes["pp"] = pp
            if deg["ep"] > 1:
                axes["ep"] = deg["ep"]
            mesh = LogicalMesh(**axes)
        # int8 is the quantized *serving* axis: price the graph in
        # inference mode (no grads, no param-update traffic) at the
        # int8 MXU peak
        grad_req = "null" if config["dtype"] in ("int8", "fp8") \
            else "write"
        ctx = AnalysisContext(sym, shapes=shapes_fn(config["batch"]),
                              grad_req=grad_req, target="tpu",
                              mesh=mesh, world_size=max(1, world),
                              compute_dtype=config["dtype"],
                              device_kind=self.device_kind,
                              hbm_bytes=self.hbm_bytes)
        # MXL-E reads the microbatch count off the context (overrides
        # the MXTPU_LINT_MICROBATCHES default)
        mb = config.get("microbatches")
        if mb:
            ctx.schedule_microbatches = int(mb)
        self._ctxs[key] = ctx
        return ctx


# ---------------------------------------------------------------------
# constraint pruning (before pricing)
# ---------------------------------------------------------------------
def predicted_peak_hbm(config, mem):
    """Calibrated per-device peak-HBM prediction for a config.

    The analytic ``peak_hbm_report`` keeps every residual live;
    compiled programs re-materialize and stage, so activations get an
    AOT-calibrated credit (``MXTPU_AUTOTUNE_ACT_CREDIT``, default 0.2
    — AOT_r05.json b512: 11.2 GB compiled temp vs 70 GB analytic).
    dp·tp shard the batch/hidden activation axes; params/grads/opt
    state shard over tp, and over dp too when the rule is fsdp
    (ZeRO-3)."""
    deg = parse_sharding(config["sharding"])
    credit = _env_float("MXTPU_AUTOTUNE_ACT_CREDIT", 0.2)
    pp = int(config.get("stages") or deg["pp"])
    act_div = max(1, deg["dp"] * deg["tp"])
    # pp splits the layer stack (each stage holds ~1/pp of the params);
    # ep shards the expert stacks, which this model treats as the bulk
    # of an MoE config's state
    state_div = max(1, deg["tp"] * pp * deg["ep"]
                    * (deg["dp"] if deg["fsdp"] else 1))
    state = (mem["params_bytes"] + mem["grads_bytes"]
             + mem["aux_bytes"]) / float(state_div)
    act = mem["activations_bytes"] * credit / float(act_div)
    return state + act


def _serve_block_findings(config):
    """Graph-free MXL-K gate: a paged-KV serve block must sit on the
    compute dtype's Mosaic granule (int8 -> (32, 128))."""
    block = config.get("serve_block")
    if not block:
        return []
    return [f for f in block_findings(
        (int(block), LANES), _SERVE_POOL, config["dtype"],
        label="serve_block %s" % block) if f[1] == "error"]


def prune_config(model, config, memo, budget_bytes):
    """The feasibility gates, cheap-to-expensive, run BEFORE any
    pricing: returns ``None`` for a feasible config, else a
    ``"mxl-k: ..." | "mxl-m: ..." | "mxl-e: ..." | "mxl-d: ..."``
    reason string.
    """
    # 1. MXL-K tile legality — needs no graph at all
    bad = _serve_block_findings(config)
    if bad:
        return "mxl-k: %s" % bad[0][2]
    try:
        ctx = memo.ctx(model, config)
    except ValueError as exc:
        # e.g. an "experts" axis on a model with no MoE variant
        return "build: %s" % exc
    # 2. MXL-M HBM fit — memory report only, roofline never touched
    if budget_bytes:
        mem = peak_hbm_report(ctx)
        pred = predicted_peak_hbm(config, mem)
        if pred > budget_bytes:
            return ("mxl-m: predicted peak %.1f GB > %.1f GB %s HBM"
                    % (pred / 1e9, budget_bytes / 1e9,
                       memo.device_kind))
    deg = parse_sharding(config["sharding"])
    # 3. MXL-E schedule lint — pipeline/MoE configs only: an imbalanced
    # partition, a deadlocking back-edge, an over-budget 1F1B stash or
    # an indivisible expert count never reaches pricing (or a chip)
    pp = int(config.get("stages") or deg["pp"])
    if pp > 1 or deg["ep"] > 1 or config.get("experts"):
        if "autotune_mxl_e" not in ctx.cache:
            issues = run_rules(ctx, select=("MXL-E*",))
            ctx.cache["autotune_mxl_e"] = [
                i for i in issues if i.severity == "error"]
        errors = ctx.cache["autotune_mxl_e"]
        if errors:
            return "mxl-e: %s" % errors[0].message
    # 4. MXL-D distributed lint — sharded configs only
    if deg["dp"] * deg["tp"] * pp * deg["ep"] > 1:
        if "autotune_mxl_d" not in ctx.cache:
            issues = run_rules(ctx, select=("MXL-D*",))
            ctx.cache["autotune_mxl_d"] = [
                i for i in issues if i.severity == "error"]
        errors = ctx.cache["autotune_mxl_d"]
        if errors:
            return "mxl-d: %s" % errors[0].message
    return None


# ---------------------------------------------------------------------
# pricing + ranking
# ---------------------------------------------------------------------
def _recompute_flops(ctx):
    """Extra forward FLOPs a remat (mirror) policy replays in backward:
    every op inside a ``force_mirroring`` segment recomputes its
    forward once.  Approximation shared with the executor's mirror map
    (``executor._mirror_segments``)."""
    if "autotune_recompute" in ctx.cache:
        return ctx.cache["autotune_recompute"]
    from ..executor import _mirror_segments
    facts = _op_costs(ctx)
    by_name = {r["node"]: r for r in facts["rows"]}
    extra = 0.0
    try:
        segments = _mirror_segments(list(ctx.op_nodes()))
    except Exception:
        segments = []
    for is_mirror, nodes in segments:
        if not is_mirror:
            continue
        for node in nodes:
            row = by_name.get(node.name)
            if row is None:
                continue
            passes = 3 if row["mxu"] else 2
            extra += row["flops"] / float(passes)
    ctx.cache["autotune_recompute"] = extra
    return extra


def price_config(model, config, memo, budget_bytes):
    """Static price for a feasible config: MFU ceiling (remat pays its
    recompute replay in the time term but earns no useful-FLOP credit;
    a pipeline config pays its 1F1B bubble), per-device step-time
    floor, throughput ceiling, predicted peak HBM + headroom, and ICI
    bytes for sharded configs."""
    ctx = memo.ctx(model, config)
    rep = roofline_report(ctx)
    mem = peak_hbm_report(ctx)
    deg = parse_sharding(config["sharding"])
    pp = int(config.get("stages") or deg["pp"])
    world = max(1, deg["dp"] * deg["tp"] * pp * deg["ep"])
    pred_peak = predicted_peak_hbm(config, mem)
    out = {
        "mfu_ceiling": rep["mfu_ceiling"],
        "tflops_per_step": round(rep["flops_per_step"] / 1e12, 3),
        "hbm_traffic_gb_per_step": round(
            rep["hbm_bytes_per_step"] / 1e9, 3),
        "peak_hbm_gb": round(pred_peak / 1e9, 3),
        "hbm_headroom_gb": (round((budget_bytes - pred_peak) / 1e9, 3)
                            if budget_bytes else None),
        "bound": rep["bound"],
        "mode": rep["mode"],
        "ici_bytes": 0,
        "step_ms_floor": None,
        "samples_per_sec_ceiling": None,
        "bubble_fraction": None,
    }
    # a pipelined config idles (1 - bubble) of each stage away: the
    # MXL-E simulator's 1F1B bubble scales the ceiling down and the
    # step floor up (same slot-synchronous model the lint validates)
    bubble = 0.0
    if pp > 1:
        from .schedule import schedule_report
        sched = schedule_report(ctx)
        if sched and sched.get("schedules"):
            bubble = float(
                sched["schedules"]["1f1b"]["bubble_fraction"])
            out["bubble_fraction"] = round(bubble, 4)
    peak_f = (rep["peak_tflops"] or 0) * 1e12
    peak_b = (rep["peak_hbm_gbps"] or 0) * 1e9
    if peak_f and peak_b:
        flops = rep["flops_per_step"] / world
        byts = rep["hbm_bytes_per_step"] / world
        extra = _recompute_flops(ctx) / world \
            if config["remat"] == "blocks" else 0.0
        t = max((flops + extra) / peak_f, byts / peak_b)
        if 0.0 < bubble < 1.0:
            t /= (1.0 - bubble)
        out["step_ms_floor"] = round(t * 1e3, 3)
        out["samples_per_sec_ceiling"] = round(config["batch"] / t, 1)
        out["mfu_ceiling"] = round(flops / (t * peak_f), 4)
    if world > 1:
        try:
            out["ici_bytes"] = int(comm_report(ctx)["total_bytes"])
        except Exception:
            out["ici_bytes"] = None
    return out


def _mark_pareto(entries):
    """Non-dominated set over (throughput ceiling max, peak HBM min)."""
    for e in entries:
        tput = e["predicted"].get("samples_per_sec_ceiling") or 0.0
        peak = e["predicted"].get("peak_hbm_gb")
        peak = float("inf") if peak is None else peak
        dominated = False
        for o in entries:
            if o is e:
                continue
            ot = o["predicted"].get("samples_per_sec_ceiling") or 0.0
            op = o["predicted"].get("peak_hbm_gb")
            op = float("inf") if op is None else op
            if ot >= tput and op <= peak and (ot > tput or op < peak):
                dominated = True
                break
        e["pareto"] = not dominated
    return entries


def search(model="resnet50", device_kind="v5e", space=None,
           hbm_gb=None, memo=None):
    """Enumerate, prune, price, rank.  Returns the full (deterministic)
    result dict; :func:`build_manifest` turns it into the replay
    manifest."""
    space = space or default_space(model)
    if hbm_gb:
        budget = int(float(hbm_gb) * (1 << 30))
    else:
        budget = hbm_capacity_bytes(device_kind)
    memo = memo or GraphMemo(device_kind=device_kind, hbm_bytes=budget)
    entries, pruned = [], []
    for config in space_configs(space):
        cfg = dict(config)
        cfg["model"] = model
        cid = config_id(cfg)
        reason = prune_config(model, config, memo, budget)
        if reason:
            pruned.append({"config_id": cid, "config": config,
                           "reason": reason})
            continue
        entries.append({"config_id": cid, "config": config,
                        "predicted": price_config(model, config, memo,
                                                  budget)})
    entries.sort(key=lambda e: (
        -(e["predicted"]["mfu_ceiling"] or 0.0),
        -(e["predicted"]["hbm_headroom_gb"] or 0.0),
        e["config_id"]))
    _mark_pareto(entries)
    for i, e in enumerate(entries):
        e["rank"] = i + 1
    peak_f, peak_b = device_peaks(device_kind)
    return {
        "model": model,
        "device_kind": device_kind,
        "space": {a: list(space.get(a) or default_space()[a])
                  for a in AXES},
        "hbm_budget_bytes": budget,
        "peaks": {"tflops": (peak_f / 1e12) if peak_f else None,
                  "hbm_gbps": (peak_b / 1e9) if peak_b else None},
        "calibration": {
            "fusion_factor": _env_float(
                "MXTPU_ROOFLINE_FUSION_FACTOR", 0.77),
            "staging_bytes_per_param": _env_float(
                "MXTPU_ROOFLINE_STAGING_BYTES_PER_PARAM", 637),
            "act_credit": _env_float("MXTPU_AUTOTUNE_ACT_CREDIT", 0.2),
        },
        "counts": {"total": len(entries) + len(pruned),
                   "priced": len(entries), "pruned": len(pruned),
                   "symbols_built": memo.stats["symbols_built"],
                   "analyses": memo.stats["analyses"],
                   "memo_hits": memo.stats["memo_hits"]},
        "entries": entries,
        "pruned": pruned,
    }


# ---------------------------------------------------------------------
# replay manifest
# ---------------------------------------------------------------------
def bench_command(model, config, cid):
    """The exact command a chip window runs for this config.  The
    replay driver adds ``BENCH_AUTOTUNE_MANIFEST_HASH`` at run time
    (the hash covers these commands, so it cannot appear inside them).
    """
    deg = parse_sharding(config["sharding"])
    world = max(1, deg["dp"] * deg["tp"])
    env = [("BENCH_BATCH", max(1, config["batch"] // world)),
           ("BENCH_DTYPE", config["dtype"]),
           ("BENCH_REMAT", 1 if config["remat"] == "blocks" else 0)]
    m = _RESNET_RE.match(model)
    if m:
        env.append(("BENCH_LAYERS", int(m.group(1))))
    env += [("MXTPU_BUCKET_MB", config["bucket_mb"]),
            ("MXTPU_PREFETCH", 1),
            ("MXTPU_PREFETCH_DEPTH", config["prefetch"])]
    if config.get("serve_block"):
        env.append(("MXTPU_SERVE_BLOCK", config["serve_block"]))
    if config.get("serve_buckets"):
        env.append(("MXTPU_SERVE_BUCKETS", config["serve_buckets"]))
    pp = int(config.get("stages") or deg["pp"])
    if pp > 1:
        env.append(("BENCH_PP_STAGES", pp))
        env.append(("BENCH_MICROBATCHES",
                    config.get("microbatches") or 8))
    if config.get("experts"):
        env.append(("BENCH_MOE_EXPERTS", config["experts"]))
        if config.get("capacity_factor"):
            env.append(("BENCH_MOE_CAPACITY",
                        config["capacity_factor"]))
    env.append(("BENCH_AUTOTUNE_CONFIG_ID", cid))
    return " ".join("%s=%s" % (k, v) for k, v in env) + " python bench.py"


def build_manifest(result, top_k=8, provenance=None):
    """Deterministic replay manifest from a :func:`search` result:
    ordered top-K configs + predictions + exact bench commands, a
    provenance block (argv / git commit / calibration — inputs, never
    wall-clock time), and a content hash over the whole body.  Same
    inputs -> byte-identical ``canonical_json(manifest)``."""
    configs = []
    for e in result["entries"][:top_k]:
        configs.append({
            "rank": e["rank"],
            "config_id": e["config_id"],
            "config": e["config"],
            "pareto": e["pareto"],
            "predicted": e["predicted"],
            "bench_cmd": bench_command(result["model"], e["config"],
                                       e["config_id"]),
        })
    body = {
        "manifest_version": 1,
        "kind": "autotune_replay_manifest",
        "model": result["model"],
        "device_kind": result["device_kind"],
        "space": result["space"],
        "hbm_budget_bytes": result["hbm_budget_bytes"],
        "peaks": result["peaks"],
        "calibration": result["calibration"],
        "counts": result["counts"],
        "provenance": dict(provenance or {}),
        "configs": configs,
        "pruned": result["pruned"],
    }
    body["manifest_hash"] = hashlib.sha256(
        canonical_json(body).encode()).hexdigest()[:16]
    return body


# ---------------------------------------------------------------------
# measured-vs-predicted correction (mid-window re-ranking)
# ---------------------------------------------------------------------
def fit_correction(pairs):
    """Fit measured ≈ a·predicted + b over ``[(predicted, measured)]``
    pairs.  One point (or a degenerate spread) fits a pure ratio; two
    or more fit least squares.  Returns ``{"kind", "a", "b", "n"}`` or
    None with no usable pairs."""
    pts = [(float(p), float(m)) for p, m in pairs
           if p is not None and m is not None and p > 0]
    if not pts:
        return None
    n = len(pts)
    mean_p = sum(p for p, _ in pts) / n
    mean_m = sum(m for _, m in pts) / n
    var = sum((p - mean_p) ** 2 for p, _ in pts)
    if n == 1 or var <= 1e-12:
        return {"kind": "ratio", "a": mean_m / mean_p, "b": 0.0, "n": n}
    a = sum((p - mean_p) * (m - mean_m) for p, m in pts) / var
    b = mean_m - a * mean_p
    return {"kind": "linear", "a": a, "b": b, "n": n}


def apply_correction(correction, predicted):
    if correction is None or predicted is None:
        return predicted
    return correction["a"] * float(predicted) + correction["b"]


def rerank(entries, correction):
    """Re-sort manifest config entries by the corrected predicted MFU
    (stable on the original rank for ties) — the mid-window move after
    each measured result lands."""
    return sorted(entries, key=lambda e: (
        -(apply_correction(correction,
                           e["predicted"].get("mfu_ceiling")) or 0.0),
        e.get("rank", 0), e["config_id"]))
