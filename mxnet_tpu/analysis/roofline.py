"""Static roofline / precision lint (MXL-R): per-op FLOPs + HBM bytes,
arithmetic intensity vs the device ridge point, MXU padding waste, and
precision hazards — from the graph alone, no chip, no XLA compile.

FLOP model (calibrated against the XLA:TPU cost analysis recorded in
docs/mfu_gap.md): each op's forward FLOPs come from its
``cost_flops`` hook (conv/FC/dot: 2 FLOPs per MAC); training triples
the MXU work (forward + dgrad + wgrad are each a same-shape matmul) and
doubles everything else (forward + an elementwise-ish backward).  For
ResNet-50 b256 this lands at 6.28 TF/step vs the compiler's 6.28.

Traffic model: each op moves its inputs + outputs through HBM once
per pass, priced at the compute dtype (the trainer casts to bf16 on
TPU); MXU ops pay 3 passes in training, others 2, plus 24 bytes per
trained parameter scalar (f32 grad write + optimizer state + master
weight round-trip).  The raw per-op sum is fusion-blind, so training
traffic is **calibrated against the compiled AOT rows in
AOT_r05.json** with two terms: a fusion factor (XLA elides ~23% of
naive per-op traffic once producers fuse into consumers) and a
batch-independent staging term per trained parameter (the
copy-start/copy-done alternate-memory traffic visible in the AOT
entry-computation breakdown scales with the weight set, not the
batch).  With the defaults (0.77 / 637 B per param) the v5e ResNet-50
ceilings land at 0.19/0.30/0.33 for b64/b256/b512 vs the compiler's
0.193/0.293/0.331.  Both knobs have env overrides
(``MXTPU_ROOFLINE_FUSION_FACTOR`` /
``MXTPU_ROOFLINE_STAGING_BYTES_PER_PARAM``); inference pricing stays
uncalibrated (the fit is a training-step fit).  The raw sum is kept in
the report as ``op_hbm_bytes_per_step``.

Peaks come from bench.py's spec-sheet table
(``_lookup_peak_tflops``/``_lookup_peak_hbm``, so lint and bench can
never disagree; ``BENCH_PEAK_TFLOPS``/``BENCH_PEAK_HBM_GBPS`` overrides
apply here too).  The ridge point peak_flops/peak_bw (v5e: 197e12/819e9
≈ 240 fl/B) classifies each op and the whole graph compute- vs
bandwidth-bound, and ``mfu_ceiling = min(1, intensity/ridge)``
reproduces the docs/mfu_gap.md MFU-ceiling table statically.

Per-op findings only fire above a significance floor
(``MXTPU_LINT_ROOFLINE_MIN_FLOPS``, default 5e10 training FLOPs) so toy
test graphs and the b2 model-zoo sweep stay clean; real batch sizes
surface the findings.
"""
from __future__ import annotations

import os as _os

import numpy as _np

from ..ops.registry import op_cost
from .core import register_rule
from .memory import _grad_req_of
from .propagation import edge_shapes, fmt_bytes
from .tiling import LANES, min_tile

__all__ = ["roofline_report", "device_peaks", "resolve_compute_dtype",
           "mxu_padding_waste", "static_mfu_ceiling",
           "static_ceiling_summary"]

# training multipliers: an MXU op's backward is two more same-shape
# matmuls (dgrad + wgrad); everything else pays one elementwise-ish
# backward pass
_TRAIN_PASSES_MXU = 3
_TRAIN_PASSES_OTHER = 2
# f32 grad write + optimizer state read/write + master weight round-trip
_PARAM_UPDATE_BYTES = 24
# training-traffic calibration vs the compiled AOT table (AOT_r05.json,
# docs/mfu_gap.md): fraction of naive per-op bytes that survive XLA
# fusion, and alternate-memory staging bytes per trained parameter
# (batch-independent: the entry computation's copy-start/done pairs
# move weights, not activations)
_FUSION_FACTOR = 0.77
_STAGING_BYTES_PER_PARAM = 637


def _env_float(name, default):
    raw = _os.environ.get(name)
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    return float(default)


def _min_flops():
    return _env_float("MXTPU_LINT_ROOFLINE_MIN_FLOPS", 5e10)


def resolve_compute_dtype(ctx):
    """The dtype matmuls run at: the explicit ``compute_dtype`` hint,
    else bfloat16 for the tpu target (the ShardedTrainer default),
    else float32."""
    cd = getattr(ctx, "compute_dtype", None)
    if cd:
        return str(_np.dtype(cd).name) if cd != "bfloat16" else "bfloat16"
    return "bfloat16" if ctx.target == "tpu" else "float32"


def _itemsize(dtype):
    if str(dtype) == "bfloat16":
        return 2
    return _np.dtype(dtype).itemsize


def resolve_device_kind(ctx):
    dk = getattr(ctx, "device_kind", None)
    return dk or _os.environ.get("MXTPU_LINT_DEVICE_KIND", "v5e")


def device_peaks(device_kind, dtype=None):
    """(peak_flops_per_s, peak_hbm_bytes_per_s) from bench.py's spec
    table (env overrides apply), or (None, None) when unknown.
    ``dtype`` ("int8"/"fp8") reads the quantized peak tables — how a
    graph with QuantizedDense nodes prices those rows."""
    try:
        import bench
        tf, _note = bench._lookup_peak_tflops(device_kind, dtype=dtype)
        gb, _note2 = bench._lookup_peak_hbm(device_kind)
    except Exception:
        return None, None
    if tf is None or gb is None:
        return None, None
    return tf * 1e12, gb * 1e9


def mxu_padding_waste(dims, compute_dtype="bfloat16"):
    """Fraction of MXU work spent on tile padding for ``(m, k, n)``
    matmul dims: k and n pad to the 128-lane granule, m to the dtype's
    sublane granule.  0.0 = perfectly tiled."""
    sub, _lanes = min_tile(compute_dtype)
    done = padded = 0
    for m, k, n in dims:
        done += m * k * n
        padded += (-(-m // sub) * sub) * (-(-k // LANES) * LANES) \
            * (-(-n // LANES) * LANES)
    if not padded:
        return 0.0
    return 1.0 - float(done) / float(padded)


def _training(ctx):
    for node in ctx.variables():
        if node.name in ctx.data_names or node.name in ctx.label_names:
            continue
        if _grad_req_of(ctx, node.name) != "null":
            return True
    return False


def _op_costs(ctx):
    """Cached per-op cost rows + graph totals."""
    if "roofline_costs" in ctx.cache:
        return ctx.cache["roofline_costs"]
    shapes = edge_shapes(ctx)
    compute_dtype = resolve_compute_dtype(ctx)
    item = _itemsize(compute_dtype)
    training = _training(ctx)
    rows = []
    complete = True
    for node in ctx.op_nodes():
        in_shapes = [shapes.get((id(c), ci)) for c, ci in node.inputs]
        out_shapes = [shapes.get((id(node), i))
                      for i in range(node.op.num_outputs)]
        if any(s is None for s in in_shapes) or \
                any(s is None for s in out_shapes):
            complete = False
            continue
        try:
            cost = op_cost(node.op, in_shapes, out_shapes)
        except Exception:
            complete = False
            continue
        passes = (_TRAIN_PASSES_MXU if cost["mxu"]
                  else _TRAIN_PASSES_OTHER) if training else 1
        flops = cost["flops"] * passes
        byts = cost["bytes_elements"] * item * passes
        reduce_len = cost["reduce_len"] or 0
        if cost["mxu_dims"]:
            reduce_len = max([reduce_len] +
                             [k for _m, k, _n in cost["mxu_dims"]])
        rows.append({
            "node": node.name,
            "op": type(node.op).op_name,
            "flops": flops,
            "bytes": byts,
            "mxu": cost["mxu"],
            "mxu_dims": cost["mxu_dims"],
            "reduce_len": int(reduce_len),
            "compute_dtype": cost.get("compute_dtype"),
        })
    param_bytes = 0
    param_count = 0
    if training:
        for node in ctx.variables():
            if node.name in ctx.data_names or node.name in ctx.label_names:
                continue
            if _grad_req_of(ctx, node.name) == "null":
                continue
            shape = shapes.get((id(node), 0))
            if shape is None:
                continue
            param_count += int(_np.prod(shape, dtype=_np.int64))
    param_bytes = param_count * _PARAM_UPDATE_BYTES
    facts = {"rows": rows, "complete": complete, "training": training,
             "compute_dtype": compute_dtype, "param_bytes": param_bytes,
             "param_count": param_count}
    ctx.cache["roofline_costs"] = facts
    return facts


def roofline_report(ctx):
    """The whole-graph static roofline (cached on the context).

    Keys: ``flops_per_step``, ``hbm_bytes_per_step``, ``intensity``,
    ``device_kind``, ``peak_tflops``, ``peak_hbm_gbps``, ``ridge``,
    ``mfu_ceiling``, ``bound``, ``compute_dtype``, ``mode``,
    ``complete``, ``per_op`` (top rows by FLOPs).  Peak-dependent keys
    are None when the device kind is unknown."""
    if "roofline_report" in ctx.cache:
        return ctx.cache["roofline_report"]
    facts = _op_costs(ctx)
    flops = sum(r["flops"] for r in facts["rows"])
    op_bytes = sum(r["bytes"] for r in facts["rows"])
    calibration = None
    if facts["training"] and ctx.target == "tpu":
        # the AOT_r05.json fit (see module docstring): fused traffic +
        # param-update round-trip + batch-independent staging
        calibration = {
            "fusion_factor": _env_float(
                "MXTPU_ROOFLINE_FUSION_FACTOR", _FUSION_FACTOR),
            "staging_bytes_per_param": _env_float(
                "MXTPU_ROOFLINE_STAGING_BYTES_PER_PARAM",
                _STAGING_BYTES_PER_PARAM),
        }
        byts = calibration["fusion_factor"] * op_bytes \
            + facts["param_bytes"] \
            + calibration["staging_bytes_per_param"] \
            * facts["param_count"]
    else:
        byts = op_bytes + facts["param_bytes"]
    device_kind = resolve_device_kind(ctx)
    base_dtype = facts["compute_dtype"]
    peak_f, peak_b = device_peaks(
        device_kind,
        dtype=base_dtype if base_dtype in ("int8", "fp8") else None)
    # mixed-precision pricing: rows that declare their own compute
    # dtype (QuantizedDense -> int8/fp8) run at that dtype's peak, so
    # the graph's effective peak is flops-over-time across the mix
    # (time = Σ flops_d / peak_d) — a fully-int8 graph gets the full
    # int8 rate, a mixed graph something in between
    quant_flops = sum(r["flops"] for r in facts["rows"]
                      if r.get("compute_dtype"))
    if peak_f and quant_flops:
        t = 0.0
        for r in facts["rows"]:
            pf = peak_f
            if r.get("compute_dtype"):
                pd, _ = device_peaks(device_kind, dtype=r["compute_dtype"])
                pf = pd or peak_f
            t += r["flops"] / pf
        if t > 0:
            peak_f = flops / t
    report = {
        "flops_per_step": flops,
        "hbm_bytes_per_step": byts,
        "op_hbm_bytes_per_step": op_bytes + facts["param_bytes"],
        "calibration": calibration,
        "param_count": facts["param_count"],
        "intensity": (flops / byts) if byts else None,
        "device_kind": device_kind,
        "peak_tflops": (peak_f / 1e12) if peak_f else None,
        "peak_hbm_gbps": (peak_b / 1e9) if peak_b else None,
        "ridge": None, "mfu_ceiling": None, "bound": None,
        "compute_dtype": facts["compute_dtype"],
        "quantized_flops": quant_flops or 0,
        "mode": "training" if facts["training"] else "inference",
        "complete": facts["complete"],
        "per_op": sorted(facts["rows"], key=lambda r: -r["flops"])[:8],
    }
    if peak_f and peak_b and byts and flops:
        ridge = peak_f / peak_b
        report["ridge"] = ridge
        report["mfu_ceiling"] = min(1.0, report["intensity"] / ridge)
        report["bound"] = ("compute" if report["intensity"] >= ridge
                           else "bandwidth")
    ctx.cache["roofline_report"] = report
    return report


def static_mfu_ceiling(symbol, shapes, device_kind=None,
                       compute_dtype=None, grad_req=None, target="tpu"):
    """Convenience wrapper for bench/mfu_audit: the roofline report of
    ``symbol`` at ``shapes`` with no analysis context plumbing."""
    from .core import AnalysisContext
    ctx = AnalysisContext(symbol, shapes=shapes, grad_req=grad_req,
                          target=target)
    ctx.compute_dtype = compute_dtype
    ctx.device_kind = device_kind
    return roofline_report(ctx)


def static_ceiling_summary(symbol, shapes, device_kind=None,
                           compute_dtype=None, grad_req=None,
                           target="tpu", emit=False):
    """The ONE static-ceiling summary path shared by bench.py,
    tools/mfu_audit.py and the autotuner: flat ``static_*`` keys ready
    to merge into a BENCH payload / audit row.  Never raises — analyzer
    failures come back as ``static_mfu_ceiling_error``.  ``emit=True``
    also mirrors the roofline to the telemetry counter stream
    (``counters.emit_static_roofline``) so the measured-vs-ceiling gap
    is trackable."""
    try:
        rep = static_mfu_ceiling(symbol, shapes, device_kind=device_kind,
                                 compute_dtype=compute_dtype,
                                 grad_req=grad_req, target=target)
    except Exception as exc:  # noqa: BLE001 — callers print, not crash
        return {"static_mfu_ceiling_error":
                "%s: %s" % (type(exc).__name__, exc)}
    out = {
        "static_tflops_per_step": round(rep["flops_per_step"] / 1e12, 3),
        "static_hbm_gb_per_step": round(
            rep["hbm_bytes_per_step"] / 1e9, 3),
        "static_mfu_ceiling": (round(rep["mfu_ceiling"], 4)
                               if rep["mfu_ceiling"] is not None
                               else None),
        "static_bound": rep["bound"],
    }
    if emit:
        try:
            from ..observability import counters as _counters
            _counters.emit_static_roofline(
                symbol, shapes, device_kind=device_kind,
                compute_dtype=compute_dtype)
        except Exception:
            pass
    return out


# ----------------------------------------------------------------------
# the MXL-R rules
# ----------------------------------------------------------------------
def _active(ctx):
    return ctx.target == "tpu" and ctx.symbol is not None


@register_rule("MXL-R001", "info",
               doc="MXU op is bandwidth-bound at this batch size")
def _rule_r001(ctx):
    if not _active(ctx):
        return
    rep = roofline_report(ctx)
    if rep["ridge"] is None:
        return
    floor = _min_flops()
    for r in _op_costs(ctx)["rows"]:
        if not r["mxu"] or r["flops"] < floor or not r["bytes"]:
            continue
        intensity = r["flops"] / r["bytes"]
        if intensity < rep["ridge"]:
            ctx.report(r["node"],
                       "%s is bandwidth-bound: arithmetic intensity "
                       "%.0f fl/B < %s ridge %.0f — HBM feeds the MXU "
                       "slower than it computes at this shape (larger "
                       "batch or fused neighbors would help)"
                       % (r["op"], intensity, rep["device_kind"],
                          rep["ridge"]))


@register_rule("MXL-R002", "warning",
               doc="MXU tile padding wastes a large fraction of the op")
def _rule_r002(ctx):
    if not _active(ctx):
        return
    threshold = _env_float("MXTPU_LINT_MXU_WASTE_PCT", 25.0) / 100.0
    floor = _min_flops()
    compute_dtype = resolve_compute_dtype(ctx)
    for r in _op_costs(ctx)["rows"]:
        if not r["mxu_dims"] or r["flops"] < floor:
            continue
        waste = mxu_padding_waste(r["mxu_dims"], compute_dtype)
        if waste >= threshold:
            worst = max(r["mxu_dims"],
                        key=lambda d: -mxu_padding_waste([d],
                                                         compute_dtype))
            ctx.report(r["node"],
                       "%s pads %.0f%% of its MXU tiles away: matmul "
                       "dims %s vs the (%d, %d, %d) granule — pick "
                       "tile-aligned channel/feature sizes"
                       % (r["op"], 100.0 * waste, worst,
                          min_tile(compute_dtype)[0], LANES, LANES))


@register_rule("MXL-R003", "warning",
               doc="fp32 dot/conv on TPU: MXU peak rate needs bf16")
def _rule_r003(ctx):
    if not _active(ctx):
        return
    if _itemsize(resolve_compute_dtype(ctx)) < 4:
        return
    floor = _min_flops()
    mxu = [r for r in _op_costs(ctx)["rows"] if r["mxu"]]
    flops = sum(r["flops"] for r in mxu)
    if not mxu or flops < floor:
        return
    ctx.report(None,
               "%d dot/conv op(s) (%.2f TF/step) run at float32: the "
               "MXU's spec-sheet peak is bf16 — fp32 halves (or worse) "
               "the achievable rate; set compute_dtype=bfloat16 and "
               "keep f32 accumulation" % (len(mxu), flops / 1e12))


@register_rule("MXL-R004", "warning",
               doc="long bf16 accumulation chain (reduction hazard)")
def _rule_r004(ctx):
    if not _active(ctx):
        return
    if _itemsize(resolve_compute_dtype(ctx)) >= 4:
        return
    hazard_n = _env_float("MXTPU_LINT_BF16_REDUCE_N", 4096)
    floor = _min_flops()
    for r in _op_costs(ctx)["rows"]:
        if r["flops"] < floor or r["reduce_len"] < hazard_n:
            continue
        ctx.report(r["node"],
                   "%s accumulates over %d elements at bfloat16 (~8 "
                   "mantissa bits): force f32 accumulation "
                   "(preferred_element_type) or split the reduction"
                   % (r["op"], r["reduce_len"]))


@register_rule("MXL-R005", "info",
               doc="whole-graph static roofline / MFU-ceiling summary")
def _rule_r005(ctx):
    if not _active(ctx):
        return
    rep = roofline_report(ctx)
    if rep["flops_per_step"] < _min_flops() or rep["ridge"] is None:
        return
    ctx.report(None,
               "static roofline (%s, %s, %s): %.2f TF + %s per step -> "
               "intensity %.0f fl/B vs ridge %.0f -> %s-bound, MFU "
               "ceiling %.2f%s"
               % (rep["device_kind"], rep["compute_dtype"], rep["mode"],
                  rep["flops_per_step"] / 1e12,
                  fmt_bytes(rep["hbm_bytes_per_step"]),
                  rep["intensity"], rep["ridge"], rep["bound"],
                  rep["mfu_ceiling"],
                  "" if rep["complete"]
                  else " (partial: some shapes unknown)"))
