"""Static analysis over Symbol graphs: lint passes + bind-time validation.

Entry points:

- :func:`analyze` — run the registered passes over an in-memory Symbol
  (with optional shape/type hints and bind/mesh context) and return
  :class:`GraphIssue` findings;
- :func:`analyze_json` — same over a *saved* symbol JSON, which
  additionally exposes dead nodes/unused arg_nodes the in-memory graph
  cannot represent;
- ``Symbol.validate()`` (symbol.py) and the ``validate=`` knob on
  ``Executor``/``bind``/``simple_bind`` (executor.py) wrap these;
- ``tools/mxlint.py`` is the standalone CLI for saved graphs and the
  bundled model zoo.

Rule catalog (see docs/graph_lint.md):

========  ========  ==================================================
rule      severity  finding
========  ========  ==================================================
MXL-S001  warning   shapes unknown after propagation
MXL-S002  error     contradictory shapes on one edge
MXL-T001  warning   implicit float-width promotion at an op input
MXL-T002  error     type propagation failure
MXL-G001  warning   node unreachable from any head (saved graphs)
MXL-G002  warning   declared input never consumed / ignored bind entry
MXL-G003  warning   output aliases an input variable
MXL-G004  error     duplicate node names
MXL-B001  error     grad_req=write on a shared grad buffer
MXL-B002  warning   partial args_grad silently downgraded to null
MXL-B003  error     auxiliary-state name collision
MXL-B004  error     invalid grad_req value
MXL-B005  warning   ctx_group absent from group2ctx
MXL-L001  error     op has no JAX lowering for the target platform
MXL-L002  error     host callback inside a mirrored segment
MXL-L003  info      host-callback op breaks fusion
MXL-L004  error     sharding spec references axes missing from mesh
MXL-P001  error     sharding conflict forces an implicit reshard
MXL-P002  warning   sharded value consumed replicated (all-gather)
MXL-P003  info      parameter degraded to replicated (not divisible)
MXL-P004  info      sharded contraction: XLA inserts matching psum
MXL-M001  error     estimated peak HBM exceeds per-device budget
MXL-M002  warning   replicated parameter dominates the HBM budget
MXL-C001  error     kvstore scope does not match the mesh scope
MXL-C002  error     collective crosses a pipeline-stage boundary
MXL-C003  warning   tp-sharded matmul missing its matching reduction
MXL-K001  error     pallas block violates the Mosaic dtype minimum tile
MXL-K002  error     pallas block lane dim not 128-aligned
MXL-K003  warning   pallas grid padding (array dim not divisible)
MXL-K004  error     pallas block exceeds its array / malformed spec
MXL-R001  info      MXU op is bandwidth-bound at this batch size
MXL-R002  warning   MXU tile padding wastes a large op fraction
MXL-R003  warning   fp32 dot/conv on TPU (MXU peak rate needs bf16)
MXL-R004  warning   long bf16 accumulation chain (reduction hazard)
MXL-R005  info      whole-graph static roofline / MFU-ceiling summary
MXL-D001  error     collective order mismatch across ranks
MXL-D002  error     collective signature mismatch across ranks
MXL-D003  error     collective under rank-conditional control flow
MXL-D004  error     rank-divergent value flows into a coordinated path
MXL-D005  error     collective gated on rank-divergent control flow
MXL-D006  warning   unbalanced collective on an exception edge
MXL-Q001  error     shared attribute raced across threads w/o lock
MXL-Q002  error     lock-order cycle (potential deadlock)
MXL-Q003  warning   blocking call while holding a lock
MXL-Q004  warning   thread started without registry or join path
MXL-Q005  error     host-callback mutates step-path state unsynced
MXL-Q006  warning   condition wait without predicate re-check loop
MXL-X001  error     python control flow on a tracer in a traced scope
MXL-X002  error     unstable cache-key ingredient (id/order/env read)
MXL-X003  error     jit/lower constructed on a per-request/step path
MXL-X004  warning   bare python scalar crosses the trace boundary
MXL-X005  error     unbucketed dynamic shape fed to an AOT table
MXL-X006  error     donated buffer reused after donation
MXL-E001  error     pipeline stage compute imbalance
MXL-E002  warning   pipeline bubble fraction above bound
MXL-E003  error     cross-stage back-edge (deadlock under 1F1B)
MXL-E004  error     per-stage activation-stash HBM overflow
MXL-E005  warning   stage-boundary transfer cannot hide under compute
MXL-E006  error     expert count not divisible by the ep axis
MXL-E007  warning   capacity factor risks dropping tokens
MXL-E008  info      expert all-to-all priced per rank
========  ========  ==================================================

The MXL-P/M/C families only activate with SPMD context: pass ``mesh``
(a ``jax.sharding.Mesh`` or the device-less ``parallel.LogicalMesh``)
to enable propagation, plus ``hbm_bytes``/``MXTPU_HBM_GB`` for the
memory budget and ``kvstore`` for the scope audit.  ``select``/``skip``
accept fnmatch wildcards (``MXL-P*``).

The MXL-K family (tiling.py) validates every Pallas kernel spec in the
``register_kernel_spec`` registry against Mosaic's tile rules — tpu
target only, graph-independent.  MXL-R (roofline.py) prices the graph's
FLOPs and HBM traffic at ``compute_dtype`` (default bf16 on tpu)
against ``device_kind`` peaks (default v5e,
``MXTPU_LINT_DEVICE_KIND``); per-op findings gate on a significance
floor (``MXTPU_LINT_ROOFLINE_MIN_FLOPS``, default 5e10) so toy graphs
stay clean.

The MXL-D family is the distributed lint (docs/graph_lint.md):
D001..D003 simulate the per-rank collective trace (gated on
``world_size > 1`` — or ``MXTPU_LINT_DISTRIBUTED=1`` +
``MXTPU_LINT_WORLD_SIZE``); D004..D006 are a rank-divergence dataflow
pass over Python source, activated by ``source_paths`` (the CLI's
``--distributed`` / ``.py`` targets).  Mark runtime rendezvous
functions with ``base.collective_seam``; suppress intentional
divergence with ``# mxl: rank-divergent-ok (MXL-D00x)``.

The MXL-Q family is the concurrency lint (concurrency.py, docs/
graph_lint.md): a source-level race/deadlock/blocking-under-lock pass
over the threaded runtime, activated by ``source_paths`` (the CLI's
``--concurrency``).  Mark dynamic thread entries with
``base.thread_entry``; suppress intentional sharing with
``# mxl: thread-shared-ok (MXL-Q00x)``.  The runtime witness for
Q002 is ``observability.locktrace`` (``MXTPU_LOCKCHECK=1``).

The MXL-X family is the retrace-stability lint (retrace.py, docs/
graph_lint.md): a source-level pass proving the zero-steady-state-
lowerings contract — no per-value retraces, stable compile-cache
keys, no hot-path jit construction, bucket-routed AOT serving —
activated by ``source_paths`` (the CLI's ``--retrace``).  Mark
indirectly-traced functions with ``base.traced_scope``; suppress
intentional hazards with ``# mxl: retrace-ok (MXL-X00x)``.  The
runtime witness is ``observability.retrace``
(``MXTPU_RETRACE_SENTRY=1``), which counts and attributes every
post-warmup lowering.

The MXL-E family is the schedule lint (schedule.py, docs/
graph_lint.md): a static simulator pricing pipeline-parallel (GPipe +
1F1B) and MoE execution before a chip is touched — stage partitions
from ``ctx_group`` or a ``pp`` mesh axis, stages priced by the MXL-R
roofline, boundaries by the ICI model, the 1F1B walk driven by the
SAME kind table the runtime compiles.  Activated by a >= 2-stage
partition or MoE nodes (``mxlint --mesh dp=2,pp=4 --schedule``);
``MXTPU_LINT_SCHEDULE=0`` kills the family.

Suppress per node with the ``__lint_ignore__`` attr (comma-separated
rule ids, or ``all``).
"""
from __future__ import annotations

import json as _json

from .core import (GraphIssue, AnalysisContext, Rule, RULE_REGISTRY,
                   register_rule, run_rules, format_issues,
                   SEVERITIES, SEVERITY_RANK)

# importing the pass modules registers their rules
from . import shapes as _shapes      # noqa: F401
from . import graph as _graph        # noqa: F401
from . import bind as _bind          # noqa: F401
from . import lowering as _lowering  # noqa: F401
from . import propagation as _propagation  # noqa: F401
from . import memory as _memory      # noqa: F401
from . import collectives as _collectives  # noqa: F401
from . import tiling as _tiling      # noqa: F401
from . import roofline as _roofline  # noqa: F401
from . import distributed as _distributed  # noqa: F401
from . import divergence as _divergence    # noqa: F401
from . import concurrency as _concurrency  # noqa: F401
from . import retrace as _retrace          # noqa: F401
from . import schedule as _schedule        # noqa: F401
from .propagation import comm_report
from .memory import peak_hbm_report, hbm_capacity_bytes
from .tiling import register_kernel_spec, kernel_spec_issues
from .roofline import (roofline_report, static_ceiling_summary,
                       static_mfu_ceiling)
from .distributed import collective_trace
from .schedule import schedule_report, stage_partition
from .divergence import analyze_source_paths, collective_seam
from .concurrency import analyze_concurrency_paths, thread_entry
from .retrace import analyze_retrace_paths, traced_scope

__all__ = ["GraphIssue", "AnalysisContext", "Rule", "RULE_REGISTRY",
           "register_rule", "run_rules", "format_issues", "SEVERITIES",
           "SEVERITY_RANK", "analyze", "analyze_json", "max_severity",
           "GraphLintWarning", "comm_report", "peak_hbm_report",
           "hbm_capacity_bytes", "register_kernel_spec",
           "kernel_spec_issues", "roofline_report", "static_mfu_ceiling",
           "static_ceiling_summary",
           "collective_trace", "schedule_report", "stage_partition",
           "analyze_source_paths", "collective_seam",
           "analyze_concurrency_paths", "thread_entry",
           "analyze_retrace_paths", "traced_scope"]


class GraphLintWarning(UserWarning):
    """Category for bind-time lint findings emitted in 'warn' mode."""


def analyze(symbol, shapes=None, type_dict=None, args=None, args_grad=None,
            grad_req=None, aux_states=None, group2ctx=None, mesh=None,
            sharding_rules=None, target="tpu", json_graph=None,
            kvstore=None, hbm_bytes=None, data_names=None,
            label_names=None, compute_dtype=None, device_kind=None,
            world_size=None, source_paths=None,
            select=None, skip=None, _ctx_out=None):
    """Run the lint passes over ``symbol``; returns issues, errors first.

    Parameters mirror what the call surfaces know: ``Symbol.validate``
    passes shape/type/mesh hints, the Executor bind hook adds
    args/args_grad/grad_req/aux_states/group2ctx, and the CLI adds the
    raw ``json_graph`` dict of a saved symbol plus the SPMD context
    (``mesh``/``kvstore``/``hbm_bytes``; ``data_names``/``label_names``
    steer the sharding seeds).  ``select``/``skip`` restrict which rule
    ids run (fnmatch wildcards like ``MXL-P*`` work).  ``_ctx_out``, when
    a list, receives the AnalysisContext so callers (the CLI's cost
    report) can reuse the cached propagation/memory facts.
    """
    ctx = AnalysisContext(symbol, shapes=shapes, type_dict=type_dict,
                          args=args, args_grad=args_grad, grad_req=grad_req,
                          aux_states=aux_states, group2ctx=group2ctx,
                          mesh=mesh, sharding_rules=sharding_rules,
                          target=target, json_graph=json_graph,
                          kvstore=kvstore, hbm_bytes=hbm_bytes,
                          data_names=data_names, label_names=label_names,
                          compute_dtype=compute_dtype,
                          device_kind=device_kind, world_size=world_size,
                          source_paths=source_paths)
    if _ctx_out is not None:
        _ctx_out.append(ctx)
    return run_rules(ctx, select=select, skip=skip)


def analyze_json(json_src, **kwargs):
    """Lint a saved symbol JSON (string or parsed dict).

    Builds the Symbol through the normal loader, then analyzes with the
    raw node list attached so dead-node/unused-arg detection sees what
    the loader silently drops.  Nodes naming ops absent from the registry
    become MXL-L001 errors (the loader would just raise); the graph-only
    passes still run so one lint reports everything it can.
    """
    from ..symbol import load_json
    from ..ops.registry import OP_REGISTRY
    if isinstance(json_src, bytes):
        json_src = json_src.decode("utf-8")
    if isinstance(json_src, str):
        graph = _json.loads(json_src)
    else:
        graph = json_src
        json_src = _json.dumps(json_src)
    registered = dict(OP_REGISTRY.items())
    unknown = [spec for spec in graph.get("nodes", [])
               if spec.get("op") not in ("null", "None")
               and spec.get("op") not in registered]
    if unknown:
        issues = [GraphIssue("MXL-L001", "error", spec.get("name"),
                             "op %r of node %r is not in the operator "
                             "registry: the graph cannot load, let alone "
                             "lower" % (spec.get("op"), spec.get("name")))
                  for spec in unknown]
        kwargs.pop("select", None)
        kwargs.pop("skip", None)
        issues += analyze(None, json_graph=graph,
                          select={"MXL-G001", "MXL-G002"}, **kwargs)
        issues.sort(key=lambda i: (-SEVERITY_RANK[i.severity], i.rule_id,
                                   i.node or ""))
        return issues
    return analyze(load_json(json_src), json_graph=graph, **kwargs)


def max_severity(issues):
    """Highest severity present in ``issues`` (None when empty)."""
    best = None
    for i in issues:
        if best is None or SEVERITY_RANK[i.severity] > SEVERITY_RANK[best]:
            best = i.severity
    return best
