"""Dead/unused-subgraph passes (rule family MXL-G).

An in-memory Symbol only ever holds nodes reachable from its heads, so
true dead nodes can only survive in a *saved* graph (the JSON nodes list
keeps everything the writer serialized) — the CLI lints those through
``ctx.json_graph``.  At bind time the silent-footgun variant is user
inputs the executor quietly ignores: ``_as_list`` drops dict keys that
aren't graph arguments without a word.

- MXL-G001  node in a saved graph unreachable from every head — warning;
- MXL-G002  declared-but-never-consumed arguments: saved-graph arg_nodes
            feeding nothing, and bind-time args/args_grad/aux dict keys
            the graph doesn't declare — warning;
- MXL-G003  output is a bare alias of an input variable (reads back the
            fed value; usually a head wired to the wrong symbol) —
            warning;
- MXL-G004  duplicate node names (arg_dict/aux_dict/JSON round-trips all
            key on names and silently collapse duplicates) — error.
"""
from __future__ import annotations

from .core import register_rule


def _json_reachable(graph):
    """Set of node indices reachable from the saved graph's heads."""
    nodes = graph.get("nodes", [])
    stack = [h[0] for h in graph.get("heads", [])]
    seen = set()
    while stack:
        i = stack.pop()
        if i in seen or not 0 <= i < len(nodes):
            continue
        seen.add(i)
        stack.extend(inp[0] for inp in nodes[i].get("inputs", []))
    return seen


@register_rule("MXL-G001", "warning", "node unreachable from any head")
def dead_node(ctx):
    """Saved-graph nodes no head depends on: dead weight that still
    costs load time and confuses checkpoint surgery."""
    if not ctx.json_graph:
        return
    nodes = ctx.json_graph.get("nodes", [])
    reachable = _json_reachable(ctx.json_graph)
    for i, spec in enumerate(nodes):
        # variables ("null" op) are MXL-G002's finding, not dead compute
        if i not in reachable and spec.get("op") not in ("null", "None"):
            ctx.report(spec.get("name"),
                       "node %r (op %s) is unreachable from every head"
                       % (spec.get("name"), spec.get("op")))


@register_rule("MXL-G002", "warning", "declared input never consumed")
def unused_inputs(ctx):
    """Arguments that exist but feed nothing."""
    # saved graph: arg_nodes consumed by no reachable node and not heads
    if ctx.json_graph:
        nodes = ctx.json_graph.get("nodes", [])
        reachable = _json_reachable(ctx.json_graph)
        consumed = set()
        for i in reachable:
            for inp in nodes[i].get("inputs", []):
                consumed.add(inp[0])
        head_idx = {h[0] for h in ctx.json_graph.get("heads", [])}
        for i in ctx.json_graph.get("arg_nodes", []):
            if i not in consumed and i not in head_idx \
                    and 0 <= i < len(nodes):
                ctx.report(nodes[i].get("name"),
                           "argument %r is declared but never consumed"
                           % nodes[i].get("name"))
    # bind time: dict entries the executor would silently drop
    declared = set(ctx.symbol.list_arguments()) if ctx.symbol else set()
    aux = set(ctx.symbol.list_auxiliary_states()) if ctx.symbol else set()
    for what, obj, names in (("args", ctx.args, declared),
                             ("args_grad", ctx.args_grad, declared),
                             ("aux_states", ctx.aux_states, aux)):
        if isinstance(obj, dict):
            for key in sorted(set(obj) - names):
                ctx.report(None, "%s entry %r matches no graph %s and "
                           "is silently ignored by bind"
                           % (what, key,
                              "auxiliary state" if what == "aux_states"
                              else "argument"))


@register_rule("MXL-G003", "warning", "output aliases an input variable")
def output_alias(ctx):
    """Heads wired straight to a variable: forward just reads back what
    was fed in (and its gradient is the head grad verbatim)."""
    seen = set()
    for pos, (node, idx) in enumerate(ctx.symbol._heads):
        if node.is_variable:
            ctx.report(node, "output %d is a bare alias of input "
                       "variable %r" % (pos, node.name))
        if (id(node), idx) in seen:
            ctx.report(node, "output %d duplicates an earlier head of "
                       "%r: both outputs alias one value" % (pos, node.name))
        seen.add((id(node), idx))


@register_rule("MXL-G004", "error", "duplicate node names")
def duplicate_names(ctx):
    """Two distinct nodes sharing a name: arg/aux dicts and the JSON
    nodes list key on names and will silently collapse them."""
    by_name = {}
    for n in ctx.topo:
        by_name.setdefault(n.name, []).append(n)
    for name, nodes in by_name.items():
        if len(nodes) > 1:
            kinds = ["variable" if n.is_variable else n.op.op_name
                     for n in nodes]
            ctx.report(nodes[0], "%d nodes share the name %r (%s): "
                       "name-keyed binding/serialization collapses them"
                       % (len(nodes), name, ", ".join(kinds)))
