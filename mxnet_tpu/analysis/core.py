"""Core types for the static graph analyzer.

The reference front-loaded graph mistakes at bind time: GraphExecutor ran
full shape/type inference (static_graph.cc:59 InferNodeShapes) and refused
to bind an inconsistent graph.  Collapsing execution into one traced XLA
computation (executor.py) lost that surface — a bad graph now dies deep in
jax tracing or, worse, runs silently wrong.  This package restores the
bind-time safety net as an extensible pass framework:

- :class:`GraphIssue` — one finding (rule id, severity, node, message);
- :func:`register_rule` — decorator adding a pass to ``RULE_REGISTRY``;
- :class:`AnalysisContext` — everything a pass may inspect: the symbol,
  its topo order, optional shape/type hints, bind-time arguments
  (args/args_grad/grad_req/aux), device/mesh/sharding info, and the raw
  JSON graph when linting a saved file (the only place dead nodes can
  still exist: an in-memory Symbol only ever sees nodes reachable from
  its heads);
- :func:`run_rules` — execute passes and collect issues, most severe
  first.

Per-node suppression rides on node attrs (the same channel as
``ctx_group``/``lr_mult``): ``__lint_ignore__="MXL-G003,MXL-L003"`` or
``"all"`` mutes matching rules for that node.  Graph-level issues
(``node is None``) cannot be attr-suppressed; select rules instead.
"""
from __future__ import annotations

import fnmatch
from collections import OrderedDict

__all__ = ["GraphIssue", "AnalysisContext", "Rule", "RULE_REGISTRY",
           "register_rule", "run_rules", "format_issues",
           "SEVERITIES", "SEVERITY_RANK"]

SEVERITIES = ("info", "warning", "error")
SEVERITY_RANK = {s: i for i, s in enumerate(SEVERITIES)}

SUPPRESS_ATTR = "__lint_ignore__"


class GraphIssue(object):
    """One analyzer finding.

    ``node`` is the node *name* (issues outlive the graph object: the CLI
    serializes them) or None for graph-level findings.  ``anchor`` is an
    optional stable source location, ``file:qualname`` (never a raw line
    number, so ``mxlint --baseline`` records survive unrelated edits);
    ``line`` is the volatile line number kept OUT of the identity-ish
    fields — display/CI-annotation data only.
    """

    __slots__ = ("rule_id", "severity", "node", "message", "anchor",
                 "line")

    def __init__(self, rule_id, severity, node, message, anchor=None,
                 line=None):
        if severity not in SEVERITY_RANK:
            raise ValueError("bad severity %r (valid: %s)"
                             % (severity, SEVERITIES))
        self.rule_id = rule_id
        self.severity = severity
        self.node = node
        self.message = message
        self.anchor = anchor
        self.line = line

    def as_dict(self):
        out = {"rule_id": self.rule_id, "severity": self.severity,
               "node": self.node, "message": self.message}
        if self.anchor is not None:
            out["anchor"] = self.anchor
        if self.line is not None:
            out["line"] = self.line
        return out

    def __repr__(self):
        where = ("@%s" % (self.anchor or self.node)) if \
            (self.anchor or self.node) else "@graph"
        return "[%s] %s %s: %s" % (self.rule_id, self.severity, where,
                                   self.message)

    __str__ = __repr__

    def __eq__(self, other):
        return isinstance(other, GraphIssue) and \
            (self.rule_id, self.severity, self.node, self.message,
             self.anchor) == \
            (other.rule_id, other.severity, other.node, other.message,
             other.anchor)

    def __hash__(self):
        return hash((self.rule_id, self.severity, self.node, self.message,
                     self.anchor))


class Rule(object):
    """A registered pass: ``fn(ctx)`` yields/returns GraphIssues."""

    __slots__ = ("rule_id", "severity", "doc", "fn")

    def __init__(self, rule_id, severity, doc, fn):
        self.rule_id = rule_id
        self.severity = severity
        self.doc = doc
        self.fn = fn


RULE_REGISTRY = OrderedDict()   # rule_id -> Rule


def register_rule(rule_id, severity="warning", doc=None):
    """Decorator: register ``fn(ctx)`` under ``rule_id``.

    ``severity`` is the rule's default; a pass may override per issue via
    ``ctx.report(..., severity=...)``.
    """
    if severity not in SEVERITY_RANK:
        raise ValueError("bad severity %r" % severity)

    def _wrap(fn):
        if rule_id in RULE_REGISTRY:
            raise ValueError("rule %s already registered" % rule_id)
        RULE_REGISTRY[rule_id] = Rule(rule_id, severity,
                                      doc or (fn.__doc__ or "").strip(), fn)
        return fn
    return _wrap


class AnalysisContext(object):
    """Everything a lint pass may inspect.

    Built once per :func:`analyze` call; passes must treat it read-only
    except through :meth:`report`.
    """

    def __init__(self, symbol, shapes=None, type_dict=None, args=None,
                 args_grad=None, grad_req=None, aux_states=None,
                 group2ctx=None, mesh=None, sharding_rules=None,
                 target="tpu", json_graph=None, kvstore=None,
                 hbm_bytes=None, data_names=None, label_names=None,
                 compute_dtype=None, device_kind=None, world_size=None,
                 source_paths=None):
        self.symbol = symbol
        self.shapes = dict(shapes or {})        # arg name -> shape tuple
        self.type_dict = dict(type_dict or {})  # arg name -> dtype
        self.args = args                        # bind args (dict|list|None)
        self.args_grad = args_grad
        self.grad_req = grad_req
        self.aux_states = aux_states
        self.group2ctx = group2ctx
        self.mesh = mesh
        self.sharding_rules = sharding_rules
        self.target = target
        self.json_graph = json_graph            # raw dict of a saved symbol
        self.kvstore = kvstore                  # kvstore type str (MXL-C001)
        self.hbm_bytes = hbm_bytes              # per-device budget (MXL-M001)
        # roofline context (MXL-R): the dtype matmuls run at (None ->
        # bf16 on tpu) and the device kind whose peaks set the ridge
        self.compute_dtype = compute_dtype
        self.device_kind = device_kind
        # which variables are batch tensors (batch_pspec) vs parameters
        # (param_pspec) when seeding the SPMD propagation — mirrors the
        # ShardedTrainer's data/label split
        self.data_names = tuple(data_names) if data_names else ("data",)
        self.label_names = (tuple(label_names) if label_names
                            else ("softmax_label",))
        # distributed-lint context (MXL-D): the pod size the per-rank
        # collective-trace simulation runs at (None/<=1 disables
        # MXL-D001..003), and the .py files the rank-divergence
        # dataflow pass (MXL-D004..006) scans.  MXTPU_LINT_DISTRIBUTED
        # turns the family on for whole runs (bind-time included);
        # MXTPU_LINT_WORLD_SIZE sets the simulated pod size (default 4).
        if world_size is None:
            import os as _os
            if _os.environ.get("MXTPU_LINT_DISTRIBUTED", "").lower() in \
                    ("1", "true", "yes", "on"):
                try:
                    world_size = int(
                        _os.environ.get("MXTPU_LINT_WORLD_SIZE") or 4)
                except ValueError:
                    world_size = 4
        self.world_size = world_size
        self.source_paths = list(source_paths) if source_paths else []
        self.topo = symbol._topo() if symbol is not None else []
        self.cache = {}                         # cross-pass memo (propagation)
        self._rule = None                       # set by run_rules
        self._issues = []

    # -- reporting ---------------------------------------------------------
    def report(self, node, message, severity=None, rule_id=None,
               anchor=None, line=None):
        """Record one issue against ``node`` (a _Node, a name, or None).

        ``anchor``/``line`` attach a stable ``file:qualname`` source
        location (plus the volatile line, for display/CI annotations) —
        used by the source-level MXL-D passes."""
        rule = RULE_REGISTRY.get(rule_id or self._rule)
        rid = rule.rule_id if rule else (rule_id or self._rule)
        sev = severity or (rule.severity if rule else "warning")
        name = getattr(node, "name", node)
        if node is not None and self._suppressed(node, rid):
            return None
        issue = GraphIssue(rid, sev, name, message, anchor=anchor,
                           line=line)
        self._issues.append(issue)
        return issue

    def _suppressed(self, node, rule_id):
        attrs = getattr(node, "attrs", None)
        if attrs is None:       # reported by name: look the node up
            node = self._node_by_name(node)
            attrs = getattr(node, "attrs", None)
        if not attrs:
            return False
        spec = attrs.get(SUPPRESS_ATTR, "")
        if not spec:
            return False
        ids = {s.strip() for s in str(spec).split(",") if s.strip()}
        return "all" in ids or rule_id in ids

    def _node_by_name(self, name):
        for n in self.topo:
            if n.name == name:
                return n
        return None

    # -- graph helpers shared by passes ------------------------------------
    def op_nodes(self):
        return [n for n in self.topo if not n.is_variable]

    def variables(self):
        return [n for n in self.topo if n.is_variable]


def _matches(rule_id, patterns):
    """True when any pattern matches: exact ids and fnmatch wildcards
    (``MXL-P*``) both work."""
    return any(fnmatch.fnmatchcase(rule_id, p) for p in patterns)


def run_rules(ctx, select=None, skip=None):
    """Run registered passes over ``ctx``; returns issues, errors first.

    ``select``/``skip`` are iterables of rule ids — or fnmatch-style
    wildcards like ``MXL-P*`` — filtering which passes run (select wins
    over skip when both name a rule).
    """
    select = list(select) if select is not None else None
    skip = list(skip or ())
    for rule_id, rule in RULE_REGISTRY.items():
        if select is not None and not _matches(rule_id, select):
            continue
        if select is None and _matches(rule_id, skip):
            continue
        ctx._rule = rule_id
        try:
            out = rule.fn(ctx)
            if out:              # generators / explicit lists both work
                for issue in out:
                    if isinstance(issue, GraphIssue):
                        ctx._issues.append(issue)
        finally:
            ctx._rule = None
    issues = ctx._issues
    issues.sort(key=lambda i: (-SEVERITY_RANK[i.severity], i.rule_id,
                               i.anchor or "", i.node or "", i.line or 0))
    return issues


def format_issues(issues):
    """Human-readable one-line-per-issue block (the CLI's text mode)."""
    if not issues:
        return "no issues"
    return "\n".join(str(i) for i in issues)
