"""Static pipeline/MoE schedule lint (rule family MXL-E).

The reference's model parallelism was manual ``ctx_group`` placement
with no schedule: stages ran whenever their data arrived and the only
validation was a bind error.  Here pipeline parallelism is an explicit
microbatch schedule (``parallel/pipeline.py``: GPipe and 1F1B) and MoE
dispatch an explicit all-to-all (``ops/moe.py``) — both cheap to get
WRONG in ways that only show up as a dead chip window: a stage 3x the
others, a bubble fraction that eats the speedup, an activation stash
that OOMs stage 0, experts that don't divide over the ``ep`` axis.

This pass prices and validates the schedule before a chip is touched:

- stage partitions come from the ``ctx_group`` annotations MXL-C002
  already parses, or — when the mesh carries a ``pp`` axis — from a
  contiguous flops-balanced split of the topo order (how
  ``GPipeTrainer.from_block_symbol`` stacks blocks);
- each stage is priced by the calibrated MXL-R roofline (same
  ``_op_costs`` rows, same device peaks, same training multipliers);
- stage-to-stage transfers are priced like every other ICI figure in
  the analyzer (bytes per device over ``MXTPU_LINT_ICI_GBPS``);
- a slot-synchronous simulator walks both the GPipe and 1F1B microbatch
  schedules.  Slot-synchronous is deliberate: the runtime advances in
  lock step (one ppermute pair per slot is a barrier), so a slot costs
  the MAX over members, not each member's own time — a dependency-driven
  continuous simulator predicts bubbles ~30% below what the real
  schedule measures.  The 1F1B kind table is the SAME table the runtime
  compiles (``parallel.pipeline.build_1f1b_tables``), so predicted and
  measured occupancy can only diverge through the per-stage times.

Peak HBM includes the 1F1B activation stash: stage ``s`` holds
``min(K - s, M)`` in-flight microbatch activations (GPipe holds all
``M``).

Rules (docs/graph_lint.md):

- MXL-E001  stage compute imbalance (names the stage + dominant ops)
- MXL-E002  bubble fraction above bound (+ the min microbatch count
            that would fix it)
- MXL-E003  cross-stage back-edge: deadlock under 1F1B
- MXL-E004  per-stage activation-stash HBM overflow
- MXL-E005  stage-boundary transfer cannot hide under adjacent compute
- MXL-E006  expert count not divisible by the expert-parallel axis
- MXL-E007  capacity factor under 1: guaranteed token drops
- MXL-E008  expert all-to-all priced per rank (replayed through the
            MXL-D collective trace when ``world_size`` is set)

Knobs: ``MXTPU_LINT_SCHEDULE`` (family kill-switch, default on),
``MXTPU_LINT_MICROBATCHES`` (default 8; the autotuner overrides per
config via ``ctx.schedule_microbatches``), ``MXTPU_LINT_STAGE_IMBALANCE``
(E001 ratio bound, default 1.5), ``MXTPU_LINT_BUBBLE_MAX`` (E002 bound,
default 0.4), ``MXTPU_LINT_ICI_GBPS`` (boundary/all-to-all pricing,
default 90), ``MXTPU_LINT_MOE_CAPACITY_MIN`` (E007 bound, default 1.0),
``MXTPU_LINT_SCHEDULE_MIN_FLOPS`` (significance floor for the pricing
rules, default 5e10 — same reasoning as the roofline floor: toy graphs
stay clean).
"""
from __future__ import annotations

import os as _os

from .core import register_rule
from .memory import _grad_req_of, _shard_factor, hbm_capacity_bytes
from .propagation import (_edge_bytes, edge_shapes, edge_types, fmt_bytes,
                          propagate)
from .roofline import (_env_float, _op_costs, device_peaks,
                       resolve_device_kind)

__all__ = ["stage_partition", "schedule_report", "simulate_schedule",
           "gpipe_kind_rows"]


def _enabled():
    return _os.environ.get("MXTPU_LINT_SCHEDULE", "1").lower() not in \
        ("0", "false", "no", "off")


def _min_flops():
    return _env_float("MXTPU_LINT_SCHEDULE_MIN_FLOPS", 5e10)


def _microbatches(ctx):
    m = getattr(ctx, "schedule_microbatches", None)
    if not m:
        m = _env_float("MXTPU_LINT_MICROBATCHES", 8)
    return max(int(m), 1)


def _ici_bytes_per_s():
    return _env_float("MXTPU_LINT_ICI_GBPS", 90.0) * 1e9


# ----------------------------------------------------------------------
# stage partition
# ----------------------------------------------------------------------
def stage_partition(ctx):
    """Resolve the pipeline-stage partition of the graph, or None.

    Two sources, ``ctx_group`` first (explicit placement wins):

    - >= 2 distinct ``ctx_group`` attrs on op nodes: stages in order of
      first topo appearance; un-grouped nodes inherit the max stage of
      their op inputs (default 0) — the reference's placement semantics;
    - a ``pp`` axis of size >= 2 on the mesh: contiguous
      flops-balanced split of the topo-ordered op nodes into ``pp``
      chunks — the shape ``GPipeTrainer`` produces from a block stack.

    Returns ``{"mode", "k", "groups", "stage_of", "stages"}`` with
    ``stage_of`` keyed by op-node NAME.
    """
    if ctx.symbol is None:
        return None
    ops = ctx.op_nodes()
    if not ops:
        return None

    order = []
    first = {}
    for n in ops:
        g = n.attrs.get("ctx_group")
        if g and g not in first:
            first[g] = len(order)
            order.append(g)
    if len(order) >= 2:
        stage_of = {}
        for n in ops:
            g = n.attrs.get("ctx_group")
            if g:
                stage_of[n.name] = first[g]
            else:
                s = 0
                for c, _ci in n.inputs:
                    if not c.is_variable and c.name in stage_of:
                        s = max(s, stage_of[c.name])
                stage_of[n.name] = s
        k = len(order)
        stages = [[] for _ in range(k)]
        for n in ops:
            stages[stage_of[n.name]].append(n.name)
        return {"mode": "ctx_group", "k": k, "groups": order,
                "stage_of": stage_of, "stages": stages}

    mesh_shape = dict(ctx.mesh.shape) if ctx.mesh is not None else {}
    k = int(mesh_shape.get("pp", 1))
    if k < 2 or len(ops) < k:
        return None
    rows = {r["node"]: r for r in _op_costs(ctx)["rows"]}
    flops = [float(rows.get(n.name, {}).get("flops", 0.0)) for n in ops]
    total = sum(flops) or float(len(ops))
    if not sum(flops):            # no priced ops: balance by node count
        flops = [1.0] * len(ops)
    stage_of = {}
    stages = [[] for _ in range(k)]
    acc, s = 0.0, 0
    for i, n in enumerate(ops):
        stage_of[n.name] = s
        stages[s].append(n.name)
        acc += flops[i]
        remaining = len(ops) - 1 - i
        if s < k - 1 and (acc >= (s + 1) * total / k
                          or remaining <= (k - 1 - s)):
            s += 1
    return {"mode": "pp", "k": k,
            "groups": ["pp%d" % i for i in range(k)],
            "stage_of": stage_of, "stages": stages}


# ----------------------------------------------------------------------
# slot-synchronous schedule simulator
# ----------------------------------------------------------------------
def gpipe_kind_rows(k, m):
    """GPipe kind table, one row per slot over ``k`` stages: 0 idle,
    1 forward, 2 backward.  Forward wave ``m + k - 1`` slots (stage s
    busy for slots ``[s, s+m)``), backward wave mirrored, last stage
    first."""
    span = m + k - 1
    rows = []
    for t in range(span):
        rows.append([1 if s <= t < s + m else 0 for s in range(k)])
    for tt in range(span):
        rows.append([2 if (k - 1 - s) <= tt < (k - 1 - s) + m else 0
                     for s in range(k)])
    return rows


def _1f1b_kind_rows(k, m):
    from ..parallel.pipeline import build_1f1b_tables
    kind, _mb = build_1f1b_tables(k, m)
    return [[int(kind[t][s]) for s in range(k)]
            for t in range(len(kind))]


def simulate_schedule(kind_rows, t_fwd, t_bwd, xfer=0.0):
    """Walk a kind table with per-stage slot costs.

    Lock-step semantics: every slot ends with the schedule's ppermute
    pair, so the slot costs ``max(active member times, boundary
    transfer)`` and idle members wait.  Returns per-stage busy time,
    total wall time, and the bubble fraction
    ``1 - busy / (k * total)``."""
    k = len(t_fwd)
    total = 0.0
    busy = [0.0] * k
    for row in kind_rows:
        slot = 0.0
        for s in range(k):
            kd = row[s]
            w = t_fwd[s] if kd == 1 else (t_bwd[s] if kd >= 2 else 0.0)
            busy[s] += w
            if w > slot:
                slot = w
        if xfer > slot:
            slot = xfer
        total += slot
    denom = k * total
    return {"slots": len(kind_rows), "total_time": total,
            "busy": list(busy),
            "bubble_fraction":
                (1.0 - sum(busy) / denom) if denom else 0.0}


def _min_microbatches_for(k, t_fwd, t_bwd, xfer, bound, start):
    """Smallest 1F1B microbatch count whose bubble meets ``bound``
    (None when even 512 doesn't)."""
    m = max(int(start), 1)
    while m <= 512:
        sim = simulate_schedule(_1f1b_kind_rows(k, m), t_fwd, t_bwd,
                                xfer)
        if sim["bubble_fraction"] <= bound:
            return m
        m = m + 1 if m < 16 else m * 2
    return None


# ----------------------------------------------------------------------
# the schedule report
# ----------------------------------------------------------------------
def _moe_nodes(ctx):
    return [n for n in ctx.op_nodes()
            if type(n.op).op_name == "MoE"]


def schedule_report(ctx):
    """The whole-graph static schedule report (cached on the context).

    None when the graph has neither a stage partition nor MoE nodes.
    Keys: ``partition``, ``microbatches``, ``stages`` (roofline-priced),
    ``boundaries`` (ICI-priced cross-stage transfers), ``back_edges``,
    ``schedules`` (``gpipe``/``1f1b`` simulator results), ``stage_hbm``
    (params + grads + activation stash per stage, vs ``budget_bytes``),
    ``moe`` (per-node routing stats incl. static ``expert_balance`` =
    capacity over balanced load, clipped to 1), ``complete``.
    """
    if "schedule_report" in ctx.cache:
        return ctx.cache["schedule_report"]
    part = stage_partition(ctx)
    moe = _moe_report(ctx)
    if part is None and not moe:
        ctx.cache["schedule_report"] = None
        return None

    m = _microbatches(ctx)
    facts = _op_costs(ctx)
    report = {"partition": None, "microbatches": m, "stages": [],
              "boundaries": [], "back_edges": [], "schedules": {},
              "stage_hbm": [], "budget_bytes": None, "moe": moe,
              "complete": facts["complete"]}
    ctx.cache["schedule_report"] = report
    if part is None:
        return report
    report["partition"] = {"mode": part["mode"], "k": part["k"],
                           "groups": list(part["groups"])}
    k = part["k"]
    rows = {r["node"]: r for r in facts["rows"]}
    training = facts["training"]
    peak_f, peak_b = device_peaks(resolve_device_kind(ctx))

    # -- per-stage roofline pricing ------------------------------------
    t_fwd, t_bwd = [], []
    for idx, names in enumerate(part["stages"]):
        fl = sum(rows[n]["flops"] for n in names if n in rows)
        by = sum(rows[n]["bytes"] for n in names if n in rows)
        if peak_f and peak_b:
            t = max(fl / peak_f, by / peak_b)
        else:                     # no spec peaks: flops as time proxy
            t = fl
        # training triples MXU work (fwd + dgrad + wgrad); the forward
        # share of a stage slot is one pass of three
        f = (t / 3.0) if training else t
        b = (t - f) if training else 0.0
        dominant = sorted((rows[n] for n in names if n in rows),
                          key=lambda r: -r["flops"])[:2]
        report["stages"].append({
            "index": idx, "group": part["groups"][idx],
            "ops": len(names), "flops": fl, "bytes": by, "time_s": t,
            "t_fwd_s": f, "t_bwd_s": b,
            "dominant": [{"node": r["node"], "op": r["op"],
                          "flops": r["flops"]} for r in dominant]})
        t_fwd.append(f)
        t_bwd.append(b)

    # -- cross-stage edges: boundary transfers + back-edges ------------
    shapes = edge_shapes(ctx)
    types = edge_types(ctx)
    mesh_shape = dict(ctx.mesh.shape) if ctx.mesh is not None else {}
    specs = propagate(ctx)["specs"] if ctx.mesh is not None else {}
    stage_of = part["stage_of"]
    ici = _ici_bytes_per_s()
    bounds = {}
    for n in ctx.op_nodes():
        q = stage_of.get(n.name)
        for c, ci in n.inputs:
            if c.is_variable:
                continue
            p = stage_of.get(c.name)
            if p is None or q is None or p == q:
                continue
            if q < p:
                report["back_edges"].append(
                    {"src_node": c.name, "dst_node": n.name,
                     "src_stage": p, "dst_stage": q})
                continue
            shape = shapes.get((id(c), ci))
            if shape is None:
                report["complete"] = False
                continue
            b = _edge_bytes(shape, types.get((id(c), ci)))
            b //= _shard_factor(specs.get((id(c), ci)), mesh_shape)
            entry = bounds.setdefault((p, q), {"src": p, "dst": q,
                                               "bytes": 0, "edges": []})
            entry["bytes"] += b
            entry["edges"].append(c.name)
    for key in sorted(bounds):
        e = bounds[key]
        e["time_s"] = (e["bytes"] / ici) if ici else 0.0
        report["boundaries"].append(e)
    xfer = max([e["time_s"] for e in report["boundaries"]] + [0.0])
    # the simulator walks one microbatch per slot: per-mb times
    xfer_mb = xfer / m

    # -- walk both schedules -------------------------------------------
    f_mb = [t / m for t in t_fwd]
    b_mb = [t / m for t in t_bwd]
    report["schedules"]["gpipe"] = simulate_schedule(
        gpipe_kind_rows(k, m), f_mb, b_mb, xfer_mb)
    report["schedules"]["1f1b"] = simulate_schedule(
        _1f1b_kind_rows(k, m), f_mb, b_mb, xfer_mb)

    # -- per-stage peak HBM with the activation stash ------------------
    budget = ctx.hbm_bytes or hbm_capacity_bytes(resolve_device_kind(ctx))
    report["budget_bytes"] = budget
    # parameters charged to the stage of their first consumer
    stage_params = [0] * k
    for v in ctx.variables():
        if v.name in ctx.data_names or v.name in ctx.label_names:
            continue
        shape = shapes.get((id(v), 0))
        if shape is None:
            continue
        consumer = None
        for n in ctx.op_nodes():
            if any(c is v for c, _ci in n.inputs):
                consumer = stage_of.get(n.name)
                break
        if consumer is None:
            continue
        b = _edge_bytes(shape, types.get((id(v), 0)))
        b //= _shard_factor(specs.get((id(v), 0)), mesh_shape)
        mult = 2 if (training and _grad_req_of(ctx, v.name) != "null") \
            else 1                # grad buffer mirrors the param
        stage_params[consumer] += b * mult
    stage_act = [0] * k
    for n in ctx.op_nodes():
        s = stage_of.get(n.name)
        if s is None:
            continue
        shape = shapes.get((id(n), 0))
        if shape is None:
            report["complete"] = False
            continue
        b = _edge_bytes(shape, types.get((id(n), 0)))
        b //= _shard_factor(specs.get((id(n), 0)), mesh_shape)
        stage_act[s] += b
    for s in range(k):
        act_mb = stage_act[s] // m
        stash_1f1b = min(k - s, m)
        report["stage_hbm"].append({
            "index": s, "param_bytes": stage_params[s],
            "act_per_microbatch": act_mb,
            "stash_1f1b": stash_1f1b, "stash_gpipe": m,
            "peak_1f1b": stage_params[s] + act_mb * stash_1f1b,
            "peak_gpipe": stage_params[s] + act_mb * m})
    return report


def _moe_report(ctx):
    """Per-MoE-node routing stats (list, possibly empty)."""
    from ..ops.moe import moe_capacity
    shapes = edge_shapes(ctx)
    out = []
    for n in _moe_nodes(ctx):
        p = n.op.param
        c, ci = n.inputs[0]
        data = shapes.get((id(c), ci))
        tokens = None
        if data is not None and len(data) >= 2:
            tokens = 1
            for d in data[:-1]:
                tokens *= int(d)
        topk = min(int(p.top_k), int(p.num_experts))
        cap = moe_capacity(tokens, p.num_experts, topk,
                           p.capacity_factor) if tokens else 0
        balance = None
        if tokens and cap:
            balanced = tokens * topk / float(p.num_experts)
            balance = min(1.0, cap / balanced) if balanced else None
        out.append({"node": n.name, "num_experts": int(p.num_experts),
                    "top_k": topk,
                    "capacity_factor": float(p.capacity_factor),
                    "tokens": tokens, "capacity": cap,
                    "expert_balance": balance})
    return out


# ----------------------------------------------------------------------
# the MXL-E rules
# ----------------------------------------------------------------------
def _active(ctx):
    return _enabled() and ctx.target == "tpu" and ctx.symbol is not None


def _pipeline_report(ctx):
    if not _active(ctx):
        return None
    rep = schedule_report(ctx)
    if rep is None or rep["partition"] is None:
        return None
    return rep


@register_rule("MXL-E001", "error",
               doc="pipeline stage compute imbalance")
def _rule_e001(ctx):
    rep = _pipeline_report(ctx)
    if rep is None:
        return
    stages = rep["stages"]
    times = [s["time_s"] for s in stages]
    if sum(s["flops"] for s in stages) < _min_flops():
        return
    bound = _env_float("MXTPU_LINT_STAGE_IMBALANCE", 1.5)
    t_max = max(times)
    t_min = min(t for t in times if t > 0) if any(times) else 0.0
    if not t_min or not t_max:
        return
    if t_max / t_min <= bound:
        return
    worst = stages[times.index(t_max)]
    dom = ", ".join("%s (%s, %.2f TF)" % (d["node"], d["op"],
                                          d["flops"] / 1e12)
                    for d in worst["dominant"]) or "no priced ops"
    ctx.report(None,
               "stage %d (%s) is %.1fx the lightest stage "
               "(%.1f vs %.1f ms per step): every other stage idles "
               "while it runs — dominant ops: %s; rebalance the "
               "%s split (bound %.2fx, "
               "MXTPU_LINT_STAGE_IMBALANCE)"
               % (worst["index"], worst["group"], t_max / t_min,
                  t_max * 1e3, t_min * 1e3, dom,
                  rep["partition"]["mode"], bound))


@register_rule("MXL-E002", "warning",
               doc="pipeline bubble fraction above bound")
def _rule_e002(ctx):
    rep = _pipeline_report(ctx)
    if rep is None:
        return
    if sum(s["flops"] for s in rep["stages"]) < _min_flops():
        return
    bound = _env_float("MXTPU_LINT_BUBBLE_MAX", 0.4)
    sim = rep["schedules"]["1f1b"]
    if sim["bubble_fraction"] <= bound:
        return
    k = rep["partition"]["k"]
    m = rep["microbatches"]
    xfer = max([e["time_s"] for e in rep["boundaries"]] + [0.0]) / m
    fix = _min_microbatches_for(
        k, [s["t_fwd_s"] / m for s in rep["stages"]],
        [s["t_bwd_s"] / m for s in rep["stages"]], xfer, bound, m + 1)
    ctx.report(None,
               "1F1B bubble fraction %.2f at %d stages x %d "
               "microbatches exceeds %.2f (GPipe: %.2f): devices idle "
               "%d%% of the step — %s (bound MXTPU_LINT_BUBBLE_MAX, "
               "microbatches MXTPU_LINT_MICROBATCHES)"
               % (sim["bubble_fraction"], k, m, bound,
                  rep["schedules"]["gpipe"]["bubble_fraction"],
                  int(100 * sim["bubble_fraction"]),
                  ("%d microbatches would reach the bound" % fix)
                  if fix else
                  "no microbatch count up to 512 reaches the bound "
                  "(rebalance stages first)"))


@register_rule("MXL-E003", "error",
               doc="cross-stage back-edge: deadlock under 1F1B")
def _rule_e003(ctx):
    rep = _pipeline_report(ctx)
    if rep is None:
        return
    for e in rep["back_edges"]:
        ctx.report(e["dst_node"],
                   "%r (stage %d) consumes %r from LATER stage %d: "
                   "the backward-flowing activation inverts the "
                   "pipeline order — under 1F1B stage %d waits on a "
                   "microbatch stage %d has not produced, a deadlock; "
                   "move the consumer to stage >= %d or cut the edge"
                   % (e["dst_node"], e["dst_stage"], e["src_node"],
                      e["src_stage"], e["dst_stage"], e["src_stage"],
                      e["src_stage"]))


@register_rule("MXL-E004", "error",
               doc="per-stage activation-stash HBM overflow")
def _rule_e004(ctx):
    rep = _pipeline_report(ctx)
    if rep is None or not rep["budget_bytes"]:
        return
    budget = rep["budget_bytes"]
    for h in rep["stage_hbm"]:
        if h["peak_1f1b"] <= budget:
            continue
        ctx.report(None,
                   "stage %d peaks at %s under 1F1B (params+grads %s + "
                   "%d stashed microbatch activations x %s) vs the %s "
                   "per-device budget: the activation stash alone "
                   "overflows HBM — more stages, fewer microbatches in "
                   "flight, or remat the stage"
                   % (h["index"], fmt_bytes(h["peak_1f1b"]),
                      fmt_bytes(h["param_bytes"]), h["stash_1f1b"],
                      fmt_bytes(h["act_per_microbatch"]),
                      fmt_bytes(budget)))


@register_rule("MXL-E005", "warning",
               doc="stage-boundary transfer cannot hide under compute")
def _rule_e005(ctx):
    rep = _pipeline_report(ctx)
    if rep is None:
        return
    stages = rep["stages"]
    if sum(s["flops"] for s in stages) < _min_flops():
        return
    m = rep["microbatches"]
    for e in rep["boundaries"]:
        t = e["time_s"] / m
        adjacent = min(stages[e["src"]]["t_fwd_s"],
                       stages[e["dst"]]["t_fwd_s"]) / m
        if not adjacent or t <= adjacent:
            continue
        ctx.report(None,
                   "stage %d->%d boundary moves %s per microbatch "
                   "(%.2f ms at %s GB/s ICI) but the lighter adjacent "
                   "stage computes for only %.2f ms: the transfer "
                   "cannot hide under compute and stretches every "
                   "slot — shrink the boundary tensor (project down "
                   "before the cut) or move the cut"
                   % (e["src"], e["dst"], fmt_bytes(e["bytes"] // m),
                      t * 1e3,
                      ("%g" % _env_float("MXTPU_LINT_ICI_GBPS", 90.0)),
                      adjacent * 1e3))


def _moe_active(ctx):
    if not _active(ctx):
        return None
    rep = schedule_report(ctx)
    if rep is None or not rep["moe"]:
        return None
    return rep


@register_rule("MXL-E006", "error",
               doc="expert count not divisible by the ep axis")
def _rule_e006(ctx):
    rep = _moe_active(ctx)
    if rep is None:
        return
    mesh_shape = dict(ctx.mesh.shape) if ctx.mesh is not None else {}
    ep = int(mesh_shape.get("ep", 1))
    if ep <= 1:
        return
    for s in rep["moe"]:
        if s["num_experts"] % ep == 0:
            continue
        ctx.report(s["node"],
                   "%d experts do not divide over the ep=%d mesh axis: "
                   "expert-parallel sharding degrades to replicated "
                   "(every rank holds every expert) and the all-to-all "
                   "dispatch is unbalanced by construction — pick a "
                   "multiple of %d experts"
                   % (s["num_experts"], ep, ep))


@register_rule("MXL-E007", "warning",
               doc="capacity factor risks dropping tokens")
def _rule_e007(ctx):
    rep = _moe_active(ctx)
    if rep is None:
        return
    bound = _env_float("MXTPU_LINT_MOE_CAPACITY_MIN", 1.0)
    for s in rep["moe"]:
        cf = s["capacity_factor"]
        if not cf or cf >= bound:
            continue
        ctx.report(s["node"],
                   "capacity_factor %.2f < %.2f: each expert accepts "
                   "%s tokens but a PERFECTLY balanced router sends "
                   "%.0f — tokens are dropped even in the best case "
                   "(only their residual path survives, Switch "
                   "Transformer sec 2.2); raise the factor or accept "
                   "the quality loss deliberately "
                   "(MXTPU_LINT_MOE_CAPACITY_MIN)"
                   % (cf, bound,
                      s["capacity"] if s["capacity"] else "?",
                      (s["tokens"] or 0) * s["top_k"]
                      / float(s["num_experts"])))


@register_rule("MXL-E008", "info",
               doc="expert all-to-all priced per rank")
def _rule_e008(ctx):
    rep = _moe_active(ctx)
    if rep is None or ctx.mesh is None:
        return
    mesh_shape = dict(ctx.mesh.shape)
    if int(mesh_shape.get("ep", 1)) <= 1:
        return
    moe_names = {s["node"] for s in rep["moe"]}
    by_node = {}
    for ev in propagate(ctx)["events"]:
        name = getattr(ev["node"], "name", None)
        if ev["kind"] == "alltoall" and name in moe_names:
            e = by_node.setdefault(name, {"bytes": 0, "count": 0})
            e["bytes"] += ev["bytes"]
            e["count"] += 1
    ici = _ici_bytes_per_s()
    replay = ""
    if ctx.world_size and ctx.world_size > 1:
        try:
            from .distributed import collective_trace
            trace = collective_trace(ctx)
            n = sum(1 for t in trace
                    if t.get("kind") == "alltoall"
                    and t.get("name") in moe_names)
            replay = ("; replayed through the MXL-D collective trace "
                      "(%d all-to-all entr%s per rank, order-checked "
                      "across %d ranks)"
                      % (n, "y" if n == 1 else "ies", ctx.world_size))
        except Exception:
            pass
    for name in sorted(by_node):
        e = by_node[name]
        ctx.report(name,
                   "expert all-to-all moves ~%s per rank over ICI "
                   "(dispatch + combine, %.2f ms at %g GB/s); an "
                   "imbalanced router turns this into the rank "
                   "divergence MXL-D was built to catch%s"
                   % (fmt_bytes(e["bytes"]),
                      (e["bytes"] / ici) * 1e3 if ici else 0.0,
                      _env_float("MXTPU_LINT_ICI_GBPS", 90.0),
                      replay))
