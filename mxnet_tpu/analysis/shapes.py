"""Shape/dtype re-verification passes (rule family MXL-S / MXL-T).

The reference refused to bind a graph whose shapes didn't propagate
(static_graph.cc:59 InferNodeShapes); jax tracing reports the same
mistakes as opaque broadcasting errors deep inside the traced function.
These passes re-run the Symbol's own propagation *before* tracing and
turn failures into positioned issues:

- MXL-S001  shapes still unknown after propagation (can't pre-allocate,
            simple_bind will fail) — info when no hints were given,
            warning once the caller supplied shapes;
- MXL-S002  contradictory shapes (two consumers demand different shapes
            of one edge) — error;
- MXL-T001  implicit float-width promotion (e.g. f32 weights feeding a
            bf16 segment: XLA upcasts, silently halving MXU rate) —
            warning;
- MXL-T002  type propagation failure — error.
"""
from __future__ import annotations

import re as _re

import numpy as _np

from ..base import MXNetError
from .core import register_rule

_MISMATCH_NODE = _re.compile(r"for input of (\S+):")


@register_rule("MXL-S001", "warning",
               "shape unknown after propagation")
def shape_unknown(ctx):
    """Arguments/outputs whose shapes stay unknown after propagation."""
    try:
        arg_shapes, out_shapes, _aux = \
            ctx.symbol.infer_shape_partial(**ctx.shapes)
    except MXNetError:
        return      # contradiction: MXL-S002's finding, not ours
    sev = "warning" if ctx.shapes else "info"
    for name, shape in zip(ctx.symbol.list_arguments(), arg_shapes):
        if shape is None:
            ctx.report(name, "shape of argument %r unknown after "
                       "propagation; pass it to infer_shape/bind or set a "
                       "__shape__ attr" % name, severity=sev)
    for name, shape in zip(ctx.symbol.list_outputs(), out_shapes):
        if shape is None:
            ctx.report(name, "shape of output %r cannot be inferred"
                       % name, severity=sev)


@register_rule("MXL-S002", "error",
               "contradictory shapes on one graph edge")
def shape_contradiction(ctx):
    """Two consumers demanding different shapes of the same value."""
    try:
        ctx.symbol.infer_shape_partial(**ctx.shapes)
    except MXNetError as exc:
        msg = str(exc)
        m = _MISMATCH_NODE.search(msg)
        ctx.report(m.group(1) if m else None, msg)


def _propagate_types(ctx):
    """Per-edge dtype map {(id(node), out_idx): dtype} via each op's
    infer_type — the same walk as Symbol.infer_type but non-throwing
    (failures become MXL-T002 issues) and keeping every edge, which the
    promotion check needs."""
    base = _np.dtype(_np.float32)
    known = {n: _np.dtype(t) for n, t in ctx.type_dict.items()}
    types = {}
    failed = []
    for node in ctx.topo:
        if node.is_variable:
            types[(id(node), 0)] = known.get(node.name, base)
    for node in ctx.topo:
        if node.is_variable:
            continue
        in_types = [types.get((id(c), ci)) for c, ci in node.inputs]
        try:
            full_in, outs, _aux = node.op.infer_type(in_types)
        except Exception as exc:  # noqa: BLE001 — any op failure is a finding
            failed.append((node, exc))
            continue
        for (c, ci), t in zip(node.inputs, full_in):
            if types.get((id(c), ci)) is None and t is not None:
                types[(id(c), ci)] = _np.dtype(t)
        for i, t in enumerate(outs):
            types[(id(node), i)] = _np.dtype(t) if t is not None else base
    return types, failed


@register_rule("MXL-T001", "warning",
               "implicit float-width promotion at an op input")
def dtype_promotion(ctx):
    """Mixed float widths feeding one op: XLA promotes silently."""
    import jax.numpy as _jnp   # bfloat16's numpy kind is not "f"
    types, _failed = _propagate_types(ctx)
    for node in ctx.op_nodes():
        floats = {}
        for (c, ci), aname in zip(node.inputs,
                                  node.op.list_arguments()):
            t = types.get((id(c), ci))
            if t is not None and _jnp.issubdtype(t, _jnp.floating):
                floats.setdefault(t, []).append("%s(%s)" % (aname, c.name))
        if len(floats) > 1:
            wide = max(floats, key=lambda t: t.itemsize)
            narrow = min(floats, key=lambda t: t.itemsize)
            if wide.itemsize == narrow.itemsize:
                continue    # e.g. f32 vs bf16-sized f16 pairs only
            ctx.report(node, "inputs mix float widths %s: %s — the "
                       "narrow side is implicitly promoted to %s "
                       "(insert an explicit Cast to pick the compute "
                       "dtype)" % (
                           "/".join(sorted(str(t) for t in floats)),
                           "; ".join("%s: %s" % (t, ", ".join(v))
                                     for t, v in sorted(
                                         floats.items(),
                                         key=lambda kv: str(kv[0]))),
                           wide))


@register_rule("MXL-T002", "error", "type propagation failed at an op")
def dtype_failure(ctx):
    """Ops whose infer_type raised — tracing would die there too."""
    _types, failed = _propagate_types(ctx)
    for node, exc in failed:
        ctx.report(node, "infer_type failed: %s" % exc)
