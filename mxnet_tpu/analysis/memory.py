"""Liveness-based peak-HBM estimation (rule family MXL-M).

The reference planned storage ahead of execution (GraphStoragePool,
graph_executor.cc) and simply failed allocation when a graph didn't fit.
XLA plans its own buffers, but only *after* a full trace+compile — an
OOM surfaces as a compiler error minutes in, with no per-tensor
attribution.  This pass walks the topo-sorted graph with the same
shape/type/sharding facts the propagation pass derives and prices the
live set *before* any tracing:

- parameters + batch inputs (every bound argument), per-device after
  sharding;
- gradients for every argument trained (``grad_req`` != null) — same
  sharding as the parameter;
- auxiliary states (BatchNorm moving stats);
- activations: in training mode (any non-null grad_req) every op-output
  edge is a residual jax AD keeps live for the backward pass, *except*
  the interiors of ``jax.checkpoint`` mirror segments
  (executor._mirror_segments) which are dropped and recomputed; in
  inference mode a forward liveness scan (free each edge after its last
  consumer) gives the true schedule peak.

``peak_hbm_report`` returns the component breakdown; MXL-M001 compares
the peak against the per-device budget (``hbm_bytes`` passed by the
caller, or the ``MXTPU_HBM_GB`` env knob) and fails the lint when the
model cannot fit.  ``tools/aot_audit.py`` cross-checks this estimate
against the XLA-compiled memory analysis on real devices.

The estimate is *analytic*: XLA's fusion typically does somewhat better
(elementwise chains never materialize), so treat it as an upper bound
with ~2x headroom on activation-heavy graphs and percent-level accuracy
on parameter-dominated ones.
"""
from __future__ import annotations

import os as _os

from .core import register_rule
from .propagation import (edge_shapes, edge_types, propagate, _axis_size,
                          _edge_bytes, fmt_bytes)

__all__ = ["peak_hbm_report", "hbm_capacity_bytes"]

# per-chip HBM capacity (GiB) by device-kind substring; the same loose
# matching as bench.py's roofline tables (case/separator-insensitive)
_HBM_GB = (
    ("v6e", 32),
    ("v5p", 95),
    ("v5e", 16),
    ("v5litepod", 16),
    ("v4", 32),
    ("v3", 16),
    ("v2", 8),
)


def hbm_capacity_bytes(device_kind):
    """Per-device HBM capacity in bytes for a TPU device-kind string
    (``jax.devices()[0].device_kind``), or None when unknown.  The
    ``MXTPU_HBM_GB`` env var overrides (floats accepted)."""
    env = _os.environ.get("MXTPU_HBM_GB")
    if env:
        try:
            return int(float(env) * (1 << 30))
        except ValueError:
            pass
    if not device_kind:
        return None
    key = str(device_kind).lower().replace(" ", "").replace("-", "")
    for sub, gb in _HBM_GB:
        if sub in key:
            return gb * (1 << 30)
    return None


def _shard_factor(spec, mesh_shape):
    f = 1
    for entry in spec or ():
        f *= _axis_size(entry, mesh_shape)
    return max(f, 1)


def _grad_req_of(ctx, name):
    """Resolve the requested grad_req for one argument name.

    Mirrors the Executor's handling: a single string applies to every
    argument, a dict maps names (missing -> null), None defaults to
    'write' (the bind default — lint assumes training unless told
    otherwise)."""
    req = ctx.grad_req
    if req is None:
        req = "write"
    if isinstance(req, str):
        return req
    if isinstance(req, dict):
        return req.get(name, "null")
    try:        # list aligned with list_arguments
        args = ctx.symbol.list_arguments()
        return dict(zip(args, req)).get(name, "null")
    except Exception:
        return "null"


def peak_hbm_report(ctx):
    """Per-device peak-HBM breakdown for the bound graph (cached).

    Returns ``{"params_bytes", "grads_bytes", "aux_bytes",
    "activations_bytes", "peak_bytes", "mode", "budget_bytes",
    "complete", "largest"}``.  ``complete`` is False when some shapes
    never resolved (the totals are then a lower bound).  ``largest``
    lists the biggest contributors for the CLI report.
    """
    if "memory" in ctx.cache:
        return ctx.cache["memory"]
    report = {"params_bytes": 0, "grads_bytes": 0, "aux_bytes": 0,
              "activations_bytes": 0, "peak_bytes": 0, "mode": None,
              "budget_bytes": None, "complete": True, "largest": []}
    ctx.cache["memory"] = report
    if ctx.symbol is None:
        report["complete"] = False
        return report
    shapes = edge_shapes(ctx)
    types = edge_types(ctx)
    mesh_shape = dict(ctx.mesh.shape) if ctx.mesh is not None else {}
    specs = propagate(ctx)["specs"] if ctx.mesh is not None else {}

    def device_bytes(key):
        shape = shapes.get(key)
        if shape is None:
            return None
        b = _edge_bytes(shape, types.get(key))
        return b // _shard_factor(specs.get(key), mesh_shape)

    contributors = []
    batchy = set(ctx.data_names) | set(ctx.label_names)
    trained = False
    for node in ctx.variables():
        b = device_bytes((id(node), 0))
        if b is None:
            report["complete"] = False
            continue
        report["params_bytes"] += b
        contributors.append((b, "param", node.name))
        if node.name not in batchy and \
                _grad_req_of(ctx, node.name) != "null":
            trained = True
            report["grads_bytes"] += b
            contributors.append((b, "grad", node.name))

    # auxiliary states (moving stats): shapes via each op's own rule
    for node in ctx.op_nodes():
        aux_names = node.op.list_auxiliary_states()
        if not aux_names:
            continue
        in_shapes = [shapes.get((id(c), ci)) for c, ci in node.inputs]
        try:
            _, _, aux_shapes = node.op.infer_shape(in_shapes)
        except Exception:
            report["complete"] = False
            continue
        for aname, ashape in zip(aux_names, aux_shapes):
            if ashape is None:
                report["complete"] = False
                continue
            b = _edge_bytes(ashape, types.get((id(node), 0)))
            report["aux_bytes"] += b
            contributors.append((b, "aux", "%s_%s" % (node.name, aname)))

    op_nodes = ctx.op_nodes()
    report["mode"] = "training" if trained else "inference"
    if trained:
        # jax AD keeps every op output live as a residual, except mirror
        # segment interiors (dropped + recomputed under jax.checkpoint)
        from ..executor import _mirror_segments
        dropped = set()
        for is_mirror, seg in _mirror_segments(op_nodes):
            if is_mirror and len(seg) > 1:
                for n in seg[:-1]:
                    dropped.add(id(n))
        for node in op_nodes:
            if id(node) in dropped:
                continue
            for i in range(node.num_outputs):
                b = device_bytes((id(node), i))
                if b is None:
                    report["complete"] = False
                    continue
                report["activations_bytes"] += b
                contributors.append((b, "activation", node.name))
        report["peak_bytes"] = (report["params_bytes"] +
                                report["grads_bytes"] +
                                report["aux_bytes"] +
                                report["activations_bytes"])
    else:
        # forward-only: liveness scan over the topo schedule
        last_use = {}
        for pos, node in enumerate(op_nodes):
            for c, ci in node.inputs:
                last_use[(id(c), ci)] = pos
        heads = {(id(n), i) for n, i in ctx.symbol._heads}
        base = report["params_bytes"] + report["aux_bytes"]
        live = dict()       # key -> bytes, op outputs only
        peak_act = 0
        for pos, node in enumerate(op_nodes):
            for i in range(node.num_outputs):
                key = (id(node), i)
                b = device_bytes(key)
                if b is None:
                    report["complete"] = False
                    b = 0
                live[key] = b
            cur = sum(live.values())
            peak_act = max(peak_act, cur)
            for key in [k for k, p in last_use.items()
                        if p == pos and k not in heads]:
                live.pop(key, None)
        report["activations_bytes"] = peak_act
        report["peak_bytes"] = base + peak_act

    budget = ctx.hbm_bytes
    if budget is None:
        budget = hbm_capacity_bytes(None)   # env knob only
    report["budget_bytes"] = budget
    contributors.sort(key=lambda t: -t[0])
    report["largest"] = [{"bytes": b, "kind": k, "name": n}
                         for b, k, n in contributors[:8]]
    return report


@register_rule("MXL-M001", "error",
               "estimated peak HBM exceeds the per-device budget")
def peak_over_budget(ctx):
    """The model cannot fit: fail before XLA spends minutes finding out."""
    # budget check BEFORE pricing the graph: with no budget there is
    # nothing to compare against, and the report walk must not tax
    # every budget-less bind in a test suite
    budget = ctx.hbm_bytes
    if budget is None:
        budget = hbm_capacity_bytes(None)   # env knob only
    if budget is None:
        return
    rep = peak_hbm_report(ctx)
    if not rep["peak_bytes"]:
        return
    if rep["peak_bytes"] > budget:
        top = ", ".join("%s %s=%s" % (t["kind"], t["name"],
                                      fmt_bytes(t["bytes"]))
                        for t in rep["largest"][:3])
        ctx.report(None,
                   "estimated per-device peak HBM %s (params %s + grads %s "
                   "+ aux %s + activations %s, %s mode) exceeds the budget "
                   "%s; largest: %s" % (
                       fmt_bytes(rep["peak_bytes"]),
                       fmt_bytes(rep["params_bytes"]),
                       fmt_bytes(rep["grads_bytes"]),
                       fmt_bytes(rep["aux_bytes"]),
                       fmt_bytes(rep["activations_bytes"]),
                       rep["mode"], fmt_bytes(budget), top))


@register_rule("MXL-M002", "warning",
               "replicated parameter dominates the HBM budget")
def big_replicated_param(ctx):
    """A parameter replicated on every device eats a large budget slice
    the sharding rules could reclaim (threshold: MXTPU_LINT_BIG_PARAM_PCT
    percent of the budget, default 25)."""
    budget = ctx.hbm_bytes
    if budget is None:
        budget = hbm_capacity_bytes(None)   # env knob only
    if budget is None or ctx.mesh is None:
        return
    try:
        pct = float(_os.environ.get("MXTPU_LINT_BIG_PARAM_PCT", "25"))
    except ValueError:
        pct = 25.0
    threshold = budget * pct / 100.0
    shapes = edge_shapes(ctx)
    types = edge_types(ctx)
    seeds = propagate(ctx)["seeds"]
    batchy = set(ctx.data_names) | set(ctx.label_names)
    for node in ctx.variables():
        if node.name in batchy:
            continue
        spec = seeds.get(node.name)
        if spec is None or any(spec):
            continue            # unsharded info missing, or sharded
        shape = shapes.get((id(node), 0))
        if shape is None:
            continue
        b = _edge_bytes(shape, types.get((id(node), 0)))
        if b >= threshold:
            ctx.report(node, "parameter %r (%s, %s) is replicated on every "
                       "device and alone takes %.0f%% of the %s budget — "
                       "add a ShardingRule for it" % (
                           node.name, tuple(shape), fmt_bytes(b),
                           100.0 * b / budget, fmt_bytes(budget)))
