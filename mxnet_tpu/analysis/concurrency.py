"""Static concurrency lint over the framework's own source
(rule family MXL-Q001..Q006).

The runtime is threaded in earnest — the batcher scheduler, the
AsyncLauncher FIFOs, DevicePrefetcher producers, the fleet router and
its heartbeat daemon, the telemetry flusher, the watchdog — and the two
worst flakes this repo has shipped were genuine data races (the PR-13
torch host-callback race, the PR-8 ``PrefetchingIter`` shutdown races)
found by luck, not tooling.  This pass family is the thread-safety
sibling of the MXL-D rank-divergence lint: pure ``ast`` over the Python
source, never importing the scanned files, intraprocedural with a
per-class closure over ``self.method()`` calls.

Rules:

- **MXL-Q001** (error) — shared-attribute race: an attribute (or module
  global) written on a thread-entry path (``threading.Thread(target=
  ...)``, ``launcher.submit(...)``, ``@thread_entry``) and read/written
  on another thread's path with no common lock held at both sites.
- **MXL-Q002** (error) — lock-order cycle: the acquired-while-held
  graph, built package-wide from ``with self._lock:`` nesting (plus one
  hop through same-class method calls), contains a cycle — a potential
  deadlock.  ``Condition(lock)`` aliases are resolved so cv/lock pairs
  are one node.
- **MXL-Q003** (warning) — blocking call under lock: ``queue.get``,
  ``future.result``, ``join``, socket/HTTP, ``subprocess``,
  ``block_until_ready`` / device sync, ``sleep`` executed while a lock
  is held.  (``cond.wait()`` on the *held* condition is a release, not
  a block — that's Q006's subject.)
- **MXL-Q004** (warning) — unjoined/unregistered thread leak: a thread
  started outside the ``io.py`` producer registry
  (``_register_producer``) with no ``join`` path in its class/module.
- **MXL-Q005** (error) — callback-context violation: a host-callback
  body (functions handed to ``pure_callback``/``io_callback``/
  ``host_callback``/``id_tap``, or ``forward``/``backward`` of an op
  class declaring ``host_callback = True``) mutating state also touched
  by the step path without a common lock — the PR-13 bug shape.
- **MXL-Q006** (warning) — ``Condition.wait()`` without an enclosing
  ``while``-predicate re-check loop (``wait_for`` is exempt: it loops
  internally).

Two markers make intent explicit (docs/graph_lint.md):

- ``@thread_entry`` (``mxnet_tpu.base.thread_entry``) declares a
  function a thread entry point the AST pass cannot infer (dynamic
  registries, dispatch tables).
- ``# mxl: thread-shared-ok`` (optionally ``(MXL-Q001,...)``) on the
  finding line, the line above it, or the enclosing ``def`` / ``class``
  line suppresses matching findings — the comment IS the review record
  for why the sharing is safe (e.g. a GIL-atomic append-only buffer).

Findings carry a stable ``file:qualname`` anchor (plus the volatile
line for CI annotations) so ``mxlint --baseline`` records survive
unrelated edits.  The runtime witness for Q002 is
``observability/locktrace.py`` (``MXTPU_LOCKCHECK=1``), which records
per-thread acquisition stacks live and raises
``ResilienceError(kind="lock_order")`` on a real inversion.
"""
from __future__ import annotations

import ast
import os
import re

from .core import register_rule
from .divergence import (iter_py_files, _parse, _dotted, _call_name,
                         _decorator_names)

__all__ = ["thread_entry", "analyze_concurrency_paths", "SUPPRESS_RE"]

# canonical home is base.py (leaf module); re-exported for symmetry
# with divergence.collective_seam
from ..base import thread_entry  # noqa: E402,F401


# ----------------------------------------------------------------------
# vocabulary
# ----------------------------------------------------------------------
SUPPRESS_RE = re.compile(
    r"#\s*mxl:\s*thread-shared-ok(?:\s*\(([^)]*)\))?")

_ENTRY_DECORATOR = "thread_entry"

_THREAD_FACTORIES = {"Thread", "Timer"}
# call names whose callable arguments run on another thread
_SUBMIT_CALLS = {"submit", "apply_async", "map_async", "call_soon_threadsafe"}
# call names whose callable arguments run on the host-callback thread
_CALLBACK_HOSTS = {"pure_callback", "io_callback", "host_callback",
                   "id_tap", "call_tf"}
# constructors of synchronization primitives (type map for attrs)
_LOCK_FACTORIES = {"Lock", "RLock", "Semaphore", "BoundedSemaphore"}
_CONDITION_FACTORIES = {"Condition"}
_EVENT_FACTORIES = {"Event", "Barrier"}
_SYNC_FACTORIES = (_LOCK_FACTORIES | _CONDITION_FACTORIES
                   | _EVENT_FACTORIES)

# names that look like a lock when no factory assignment is visible
_LOCKISH_NAME = re.compile(r"lock|mutex|guard|cond|(^|_)sem$|(^|_)cv$",
                           re.IGNORECASE)
_CONDISH_NAME = re.compile(r"cond|(^|_)cv$", re.IGNORECASE)

# container-mutating method names: `self.buf.append(x)` is a write to
# `self.buf` for race purposes
_MUTATORS = {"append", "appendleft", "extend", "extendleft", "add",
             "update", "insert", "remove", "discard", "clear", "pop",
             "popitem", "popleft", "setdefault", "sort", "reverse"}

# unambiguous blocking calls (terminal name -> description)
_BLOCKING_CALLS = {
    "sleep": "time.sleep",
    "result": "Future.result",
    "wait_all": "launcher drain",
    "blocking_key_value_get": "coordination-KV blocking get",
    "getresponse": "an HTTP round-trip",
    "urlopen": "an HTTP round-trip",
    "check_call": "a subprocess round-trip",
    "check_output": "a subprocess round-trip",
    "communicate": "a subprocess round-trip",
    "serve_forever": "the HTTP serve loop",
    "block_until_ready": "a device sync",
    "accept": "a socket accept",
    "recv": "a socket recv",
    "recv_into": "a socket recv",
    "connect": "a socket connect",
}
_QUEUEISH_NAME = re.compile(r"queue|_q$|fifo|inbox|mailbox", re.IGNORECASE)

# thread-registry calls (io.py producer registry): a thread handed to
# one of these has a managed shutdown path
_REGISTRY_CALLS = {"_register_producer", "register_producer",
                   "_register_prefetcher"}


# ----------------------------------------------------------------------
# small helpers
# ----------------------------------------------------------------------
def _suppressions(source):
    """line -> set of rule ids (or {'all'}) from thread-shared-ok
    marker comments."""
    out = {}
    for i, line in enumerate(source.splitlines(), 1):
        m = SUPPRESS_RE.search(line)
        if not m:
            continue
        ids = {s.strip() for s in (m.group(1) or "").split(",")
               if s.strip()}
        out[i] = ids or {"all"}
    return out


def _self_attr(node):
    """`self.X` -> 'X' (drilling through subscripts), else None."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _base_name(node):
    """Innermost Name of an attribute/subscript chain, else None."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_sync_factory(value):
    """Terminal callee name of `value` if it constructs a sync
    primitive (Lock/RLock/Condition/Event/...), else None."""
    if isinstance(value, ast.Call):
        name = _call_name(value)
        if name in _SYNC_FACTORIES:
            return name
    return None


def _callable_refs(node, method_names):
    """Names of same-class methods / module functions referenced by a
    callable argument: `self.X`, bare `fn`, `lambda: self.X(...)`,
    `functools.partial(self.X, ...)`."""
    out = set()
    if isinstance(node, ast.Attribute):
        attr = _self_attr(node)
        if attr:
            out.add(("method", attr))
        return out
    if isinstance(node, ast.Name):
        out.add(("function", node.id))
        return out
    if isinstance(node, ast.Lambda):
        for sub in ast.walk(node.body):
            attr = _self_attr(sub) if isinstance(sub, ast.Attribute) \
                else None
            if attr and attr in method_names:
                out.add(("method", attr))
            elif (isinstance(sub, ast.Name)
                  and isinstance(getattr(sub, "ctx", None), ast.Load)):
                out.add(("maybe_function", sub.id))
        return out
    if isinstance(node, ast.Call) and _call_name(node) == "partial":
        for arg in node.args[:1]:
            out |= _callable_refs(arg, method_names)
        return out
    return out


def _blocking_reason(call, held, lock_norm):
    """Description if `call` blocks, given the currently-held lock set
    and a normalizer for the receiver expression.  `cond.wait()` on a
    HELD condition releases it (not a block here; Q006 owns it)."""
    name = _call_name(call)
    if name is None:
        return None
    func = call.func
    recv = func.value if isinstance(func, ast.Attribute) else None
    if name in ("wait",):
        norm = lock_norm(recv) if recv is not None else None
        if norm is not None and norm in held:
            return None          # releasing wait on the held condition
        if norm is not None:
            return "a condition/event wait"
        return None              # unknown receiver: too ambiguous
    if name in _BLOCKING_CALLS:
        return _BLOCKING_CALLS[name]
    if name == "join":
        # thread.join() / thread.join(timeout) — not str.join(seq) or
        # os.path.join(a, b): those take non-numeric positionals.
        if call.keywords and all(k.arg in ("timeout",)
                                 for k in call.keywords) \
                and not call.args:
            return "a thread join"
        if not call.args and not call.keywords:
            return "a thread join"
        if len(call.args) == 1 and not call.keywords:
            a = call.args[0]
            if isinstance(a, ast.Constant) and isinstance(
                    a.value, (int, float)):
                return "a thread join"
        return None
    if name == "run":
        dotted = _dotted(func) or ""
        if "subprocess" in dotted:
            return "a subprocess round-trip"
        return None
    if name in ("get", "put"):
        base = _base_name(func.value) if isinstance(
            func, ast.Attribute) else None
        attr = _self_attr(func.value) if isinstance(
            func, ast.Attribute) else None
        label = attr or base or ""
        if _QUEUEISH_NAME.search(label):
            # queue.put(block=False) / get_nowait-style are fine
            for k in call.keywords:
                if k.arg == "block" and isinstance(k.value, ast.Constant) \
                        and k.value.value is False:
                    return None
            return "a queue %s" % name
        return None
    return None


# ----------------------------------------------------------------------
# per-scope scan
# ----------------------------------------------------------------------
class _Access(object):
    __slots__ = ("name", "kind", "locks", "line", "method")

    def __init__(self, name, kind, locks, line, method):
        self.name = name        # attr name or module-global name
        self.kind = kind        # 'read' | 'write'
        self.locks = locks      # frozenset of normalized lock ids
        self.line = line
        self.method = method


class _ThreadSite(object):
    __slots__ = ("line", "method", "target", "assigned", "registered")

    def __init__(self, line, method, target):
        self.line = line
        self.method = method
        self.target = target     # ('method'|'function'|None, name)
        self.assigned = None     # local var / 'self.X' the Thread lands in
        self.registered = False


class _ScopeScan(object):
    """Scan one class (methods keyed by name) or one module's top-level
    functions.  `is_class` switches between `self.X` attribute tracking
    and module-global tracking."""

    def __init__(self, name, funcs, is_class, module):
        self.name = name              # class name or '<module>'
        self.funcs = funcs            # {fn_name: ast.FunctionDef}
        self.is_class = is_class
        self.module = module          # owning _ModuleScan
        self.lock_attrs = {}          # attr -> factory name
        self.alias = {}               # attr -> canonical lock attr
        self.entries = set()          # thread-entry fn names
        self.callbacks = set()        # callback-entry fn names
        self.calls = {}               # fn -> set(fn called)
        self.accesses = {}            # shared name -> [_Access]
        self.blocking = []            # (fn, line, what, locks)
        self.acq_edges = []           # (held, acquired, fn, line)
        self.top_acquires = {}        # fn -> set(locks at depth 0)
        self.method_call_sites = []   # (fn, callee, heldset)
        self.waits = []               # (fn, line, norm, while_depth)
        self.thread_sites = []        # [_ThreadSite]
        self.join_targets = set()     # names with .join() called on them
        self.registered_names = set() # names handed to _register_producer
        self.registry_funcs = set()   # fns that call the producer registry

    # -- lock identity ------------------------------------------------
    def lock_prefix(self):
        return "%s.%s" % (self.module.stub, self.name) if self.is_class \
            else self.module.stub

    def canon(self, attr):
        seen = set()
        while attr in self.alias and attr not in seen:
            seen.add(attr)
            attr = self.alias[attr]
        return attr

    def norm_lock(self, expr, fn_locals=None):
        """Normalize an expression to a lock id, else None."""
        if expr is None:
            return None
        attr = _self_attr(expr) if self.is_class else None
        if attr is not None:
            if attr in self.lock_attrs or attr in self.alias \
                    or _LOCKISH_NAME.search(attr):
                return "%s.%s" % (self.lock_prefix(), self.canon(attr))
            return None
        if isinstance(expr, ast.Name):
            nm = expr.id
            if fn_locals is not None and nm in fn_locals:
                return "%s.<local>.%s" % (self.lock_prefix(), nm)
            owner = self.module
            if nm in owner.lock_globals or _LOCKISH_NAME.search(nm):
                return "%s.%s" % (owner.stub, owner.canon_global(nm))
            return None
        if isinstance(expr, ast.Attribute):
            dotted = _dotted(expr)
            if dotted and _LOCKISH_NAME.search(dotted.rsplit(".", 1)[-1]):
                return "%s.%s" % (self.lock_prefix(), dotted)
            return None
        return None

    def is_sync_attr(self, attr):
        return attr in self.lock_attrs or attr in self.alias

    def cond_attr(self, attr):
        fac = self.lock_attrs.get(self.canon_raw(attr))
        if fac in _CONDITION_FACTORIES:
            return True
        return bool(_CONDISH_NAME.search(attr))

    def canon_raw(self, attr):
        return attr  # factory recorded under the original attr name

    # -- collection ---------------------------------------------------
    def collect_sync_decls(self):
        """Find lock/condition attrs & aliases from every method (init
        mostly) or module body."""
        for fname, fn in self.funcs.items():
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                fac = _is_sync_factory(node.value)
                for tgt in node.targets:
                    attr = _self_attr(tgt) if self.is_class else None
                    if attr is None:
                        continue
                    if fac:
                        self.lock_attrs[attr] = fac
                        if fac in _CONDITION_FACTORIES \
                                and node.value.args:
                            src = _self_attr(node.value.args[0])
                            if src:
                                self.alias[attr] = src
                                self.lock_attrs.setdefault(src, "Lock")

    def add_access(self, name, kind, locks, line, fn):
        self.accesses.setdefault(name, []).append(
            _Access(name, kind, frozenset(locks), line, fn))

    def scan_all(self):
        self.collect_sync_decls()
        for fname, fn in self.funcs.items():
            _FnScan(self, fname, fn).run()
        # resolve 'maybe_function' entries now that funcs are known
        # (handled at record time); resolve thread-site registration
        for ts in self.thread_sites:
            if ts.assigned and ts.assigned in self.registered_names:
                ts.registered = True
            if ts.assigned and ts.assigned in self.join_targets:
                ts.registered = True
            # a registry call in the creating function covers loop-built
            # thread lists (`for t in ...: _register_producer(t)`)
            if ts.method in self.registry_funcs:
                ts.registered = True

    def effective_locks(self):
        """Extra locks an internal helper provably runs under: the
        intersection of the held sets at every same-scope call site
        (helpers only — entries/callbacks/public methods are called
        from outside with nothing held).  Two fixpoint rounds cover
        helper->helper chains."""
        internal = {m for m in self.funcs
                    if m.startswith("_") and not m.startswith("__")
                    and m not in self.entries
                    and m not in self.callbacks}
        sites = {}
        for caller, callee, held in self.method_call_sites:
            sites.setdefault(callee, []).append((caller, held))
        extra = {m: frozenset() for m in self.funcs}
        for _ in range(3):
            for m in internal:
                ss = sites.get(m)
                if not ss:
                    continue
                sets = [held | extra.get(caller, frozenset())
                        for caller, held in ss]
                extra[m] = frozenset.intersection(*sets)
        return extra

    # -- closure / contexts -------------------------------------------
    def _closure(self, roots):
        out = set(roots)
        frontier = list(roots)
        while frontier:
            m = frontier.pop()
            for callee in self.calls.get(m, ()):  # same-scope calls
                if callee not in out and callee in self.funcs:
                    out.add(callee)
                    frontier.append(callee)
        return out

    def contexts(self):
        """fn -> set of root tags ('main', 'thread:<e>', 'callback:<c>')."""
        called = set()
        for c in self.calls.values():
            called |= c
        ctx = {}
        for e in self.entries:
            if e not in self.funcs:
                continue
            for m in self._closure({e}):
                ctx.setdefault(m, set()).add("thread:%s" % e)
        for c in self.callbacks:
            if c not in self.funcs:
                continue
            for m in self._closure({c}):
                ctx.setdefault(m, set()).add("callback:%s" % c)
        main_roots = set()
        for m in self.funcs:
            if m in ("__init__", "__del__"):
                continue
            if m in self.entries or m in self.callbacks:
                continue
            if m.startswith("_") and not m.startswith("__") \
                    and m in called:
                continue           # internal helper: context = callers'
            main_roots.add(m)
        for m in self._closure(main_roots):
            ctx.setdefault(m, set()).add("main")
        return ctx


class _FnScan(object):
    """Flow-sensitive-enough walk of one function: tracks the set of
    held locks through `with` nesting and block-scoped acquire()/
    release(), records shared accesses / blocking calls / lock-order
    edges / thread+callback entries."""

    def __init__(self, scope, fname, fn):
        self.scope = scope
        self.fname = fname
        self.fn = fn
        self.locals = set()
        self.global_decls = set()
        self.nested = {}          # name -> (node, def_held)
        self.nested_call_held = {}  # name -> [heldsets at call sites]
        self._thread_calls_seen = set()   # id(Call) already recorded
        self.name_alias = {}      # local var -> 'self.X' it came from
        for arg in ast.walk(fn.args):
            if isinstance(arg, ast.arg):
                self.locals.add(arg.arg)

    # -- entry --------------------------------------------------------
    def run(self):
        sc = self.scope
        decs = _decorator_names(self.fn)
        if _ENTRY_DECORATOR in decs:
            sc.entries.add(self.fname)
        self._stmts(self.fn.body, frozenset(), 0)
        # nested defs: body runs under the locks held at EVERY call
        # site (intersection); if never called locally, the def site's.
        # (walking a nested body can register deeper nested defs, so
        # drain as a worklist)
        done = set()
        while True:
            pending = [n for n in self.nested if n not in done]
            if not pending:
                break
            for name in pending:
                done.add(name)
                node, def_held = self.nested[name]
                helds = self.nested_call_held.get(name)
                if helds:
                    held = frozenset.intersection(
                        *[frozenset(h) for h in helds])
                else:
                    held = def_held
                self._stmts(node.body, frozenset(held), 0)

    # -- statements ---------------------------------------------------
    def _stmts(self, body, held, while_depth):
        held = set(held)
        for stmt in body:
            self._stmt(stmt, held, while_depth)

    def _stmt(self, stmt, held, while_depth):
        sc = self.scope
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = set(held)
            for item in stmt.items:
                lock = sc.norm_lock(item.context_expr, self.locals)
                self._expr(item.context_expr, frozenset(held),
                           while_depth)
                if lock is not None:
                    self._record_acquire(lock, inner, stmt.lineno)
                    inner.add(lock)
                if item.optional_vars is not None:
                    self._targets(item.optional_vars, frozenset(held),
                                  stmt.lineno)
            self._stmts(stmt.body, frozenset(inner), while_depth)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.locals.add(stmt.name)
            self.nested[stmt.name] = (stmt, frozenset(held))
            return
        if isinstance(stmt, ast.ClassDef):
            return
        if isinstance(stmt, ast.Global):
            self.global_decls.update(stmt.names)
            return
        if isinstance(stmt, ast.Assign):
            self._maybe_thread_assign(stmt, held)
            self._record_aliases(stmt)
            self._expr(stmt.value, frozenset(held), while_depth)
            for tgt in stmt.targets:
                self._targets(tgt, frozenset(held), stmt.lineno)
            return
        if isinstance(stmt, ast.AugAssign):
            self._expr(stmt.value, frozenset(held), while_depth)
            self._targets(stmt.target, frozenset(held), stmt.lineno)
            # aug-assign also reads
            self._load_of_target(stmt.target, frozenset(held),
                                 stmt.lineno)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._expr(stmt.value, frozenset(held), while_depth)
                self._targets(stmt.target, frozenset(held), stmt.lineno)
            return
        if isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                self._targets(tgt, frozenset(held), stmt.lineno)
            return
        if isinstance(stmt, ast.Expr):
            call = stmt.value
            if isinstance(call, ast.Call):
                name = _call_name(call)
                recv = call.func.value if isinstance(
                    call.func, ast.Attribute) else None
                if name == "acquire":
                    lock = sc.norm_lock(recv, self.locals)
                    if lock is not None:
                        self._record_acquire(lock, held, stmt.lineno)
                        held.add(lock)      # rest of this block
                        return
                if name == "release":
                    lock = sc.norm_lock(recv, self.locals)
                    if lock is not None:
                        held.discard(lock)
                        return
            self._expr(stmt.value, frozenset(held), while_depth)
            return
        if isinstance(stmt, ast.If):
            self._expr(stmt.test, frozenset(held), while_depth)
            self._stmts(stmt.body, frozenset(held), while_depth)
            self._stmts(stmt.orelse, frozenset(held), while_depth)
            return
        if isinstance(stmt, ast.While):
            self._expr(stmt.test, frozenset(held), while_depth + 1)
            self._stmts(stmt.body, frozenset(held), while_depth + 1)
            self._stmts(stmt.orelse, frozenset(held), while_depth)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter, frozenset(held), while_depth)
            self._targets(stmt.target, frozenset(held), stmt.lineno,
                          loop_target=True)
            self._stmts(stmt.body, frozenset(held), while_depth)
            self._stmts(stmt.orelse, frozenset(held), while_depth)
            # `for t in self.threads: t.join()` — record join target
            self._loop_join_probe(stmt)
            return
        if isinstance(stmt, ast.Try):
            self._stmts(stmt.body, frozenset(held), while_depth)
            for h in stmt.handlers:
                self._stmts(h.body, frozenset(held), while_depth)
            self._stmts(stmt.orelse, frozenset(held), while_depth)
            self._stmts(stmt.finalbody, frozenset(held), while_depth)
            return
        if isinstance(stmt, (ast.Return, ast.Raise, ast.Assert)):
            for child in ast.iter_child_nodes(stmt):
                self._expr(child, frozenset(held), while_depth)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._expr(child, frozenset(held), while_depth)
            elif isinstance(child, ast.stmt):
                self._stmt(child, held, while_depth)

    def _record_acquire(self, lock, held, line):
        sc = self.scope
        for h in held:
            if h != lock:
                sc.acq_edges.append((h, lock, self.fname, line))
        if not held:
            sc.top_acquires.setdefault(self.fname, set()).add(lock)

    def _loop_join_probe(self, stmt):
        """for t in <anything>: t.join() — the loop var's join makes
        the iterated collection a join target."""
        if not isinstance(stmt.target, ast.Name):
            return
        var = stmt.target.id
        joins = False
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Call) and _call_name(sub) == "join":
                f = sub.func
                if isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id == var:
                    joins = True
        if not joins:
            return
        attr = _self_attr(stmt.iter)
        if attr:
            self.scope.join_targets.add("self.%s" % attr)
        else:
            base = _base_name(stmt.iter)
            if base:
                self.scope.join_targets.add(base)

    # -- assignment targets -------------------------------------------
    def _targets(self, tgt, held, line, loop_target=False):
        sc = self.scope
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._targets(el, held, line, loop_target)
            return
        if isinstance(tgt, ast.Starred):
            self._targets(tgt.value, held, line, loop_target)
            return
        if isinstance(tgt, ast.Name):
            if tgt.id in self.global_decls:
                self._global_access(tgt.id, "write", held, line)
            else:
                self.locals.add(tgt.id)
            return
        if isinstance(tgt, ast.Attribute):
            attr = _self_attr(tgt)
            if attr and sc.is_class and not sc.is_sync_attr(attr):
                sc.add_access(attr, "write", held, line, self.fname)
            return
        if isinstance(tgt, ast.Subscript):
            attr = _self_attr(tgt)
            if attr and sc.is_class and not sc.is_sync_attr(attr):
                sc.add_access(attr, "write", held, line, self.fname)
                return
            base = _base_name(tgt)
            if base and not sc.is_class:
                self._global_access(base, "write", held, line)
            elif base and base not in self.locals:
                self._global_access(base, "write", held, line)
            self._expr(tgt.value, held, 0)

    def _load_of_target(self, tgt, held, line):
        attr = _self_attr(tgt)
        if attr and self.scope.is_class \
                and not self.scope.is_sync_attr(attr):
            self.scope.add_access(attr, "read", held, line, self.fname)

    def _global_access(self, name, kind, held, line):
        mod = self.scope.module
        if name in mod.globals_ and name not in self.locals:
            mod.global_accesses.setdefault(name, []).append(
                _Access(name, kind, frozenset(held), line,
                        "%s.%s" % (self.scope.name, self.fname)
                        if self.scope.is_class else self.fname))

    # -- thread creation ----------------------------------------------
    def _maybe_thread_assign(self, stmt, held):
        """self._t = Thread(...) / t = Thread(...): remember where the
        thread object lands for the Q004 join/registry check."""
        val = stmt.value
        if not (isinstance(val, ast.Call)
                and _call_name(val) in _THREAD_FACTORIES):
            return
        ts = self._thread_site(val)
        for tgt in stmt.targets:
            attr = _self_attr(tgt)
            if attr:
                ts.assigned = "self.%s" % attr
            elif isinstance(tgt, ast.Name):
                ts.assigned = tgt.id

    def _record_aliases(self, stmt):
        """`t = self._thread` (also in tuple unpacking, e.g. the
        `t, self._thread = self._thread, None` handoff) makes `t.join()`
        count as a join of `self._thread` for Q004."""
        pairs = []
        for tgt in stmt.targets:
            if isinstance(tgt, (ast.Tuple, ast.List)) \
                    and isinstance(stmt.value, (ast.Tuple, ast.List)) \
                    and len(tgt.elts) == len(stmt.value.elts):
                pairs.extend(zip(tgt.elts, stmt.value.elts))
            else:
                pairs.append((tgt, stmt.value))
        for t, v in pairs:
            if isinstance(t, ast.Name):
                attr = _self_attr(v)
                if attr:
                    self.name_alias[t.id] = "self.%s" % attr
                elif t.id in self.name_alias:
                    del self.name_alias[t.id]

    def _thread_site(self, call):
        sc = self.scope
        if id(call) in self._thread_calls_seen:
            for ts in sc.thread_sites:
                if ts.line == call.lineno and ts.method == self.fname:
                    return ts
        self._thread_calls_seen.add(id(call))
        target = (None, None)
        tgt_expr = None
        for k in call.keywords:
            if k.arg == "target":
                tgt_expr = k.value
        if tgt_expr is None and len(call.args) >= 2:
            tgt_expr = call.args[1]
        if tgt_expr is not None:
            for kind, name in _callable_refs(tgt_expr, sc.funcs):
                if kind == "method" and name in sc.funcs:
                    sc.entries.add(name)
                    target = ("method", name)
                elif kind in ("function", "maybe_function"):
                    mod = sc.module
                    if name in mod.module_funcs:
                        mod.module_scope.entries.add(name)
                        target = ("function", name)
        ts = _ThreadSite(call.lineno, self.fname, target)
        sc.thread_sites.append(ts)
        return ts

    # -- expressions --------------------------------------------------
    def _expr(self, node, held, while_depth):
        if node is None or not isinstance(node, ast.AST):
            return
        sc = self.scope
        if isinstance(node, ast.Call):
            self._call(node, held, while_depth)
            return
        if isinstance(node, ast.Lambda):
            # inline body with current held (conservative)
            self._expr(node.body, held, while_depth)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(node, ast.Attribute):
            attr = _self_attr(node)
            if attr and sc.is_class and isinstance(node.ctx, ast.Load) \
                    and attr not in sc.funcs \
                    and not sc.is_sync_attr(attr):
                sc.add_access(attr, "read", held, node.lineno,
                              self.fname)
            self._expr(node.value, held, while_depth)
            return
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load):
                self._global_read(node, held)
            return
        for child in ast.iter_child_nodes(node):
            self._expr(child, held, while_depth)

    def _global_read(self, node, held):
        mod = self.scope.module
        nm = node.id
        if nm in mod.globals_ and nm not in self.locals \
                and nm not in mod.module_funcs \
                and nm not in mod.lock_globals:
            mod.global_accesses.setdefault(nm, []).append(
                _Access(nm, "read", frozenset(held), node.lineno,
                        "%s.%s" % (self.scope.name, self.fname)
                        if self.scope.is_class else self.fname))

    def _call(self, call, held, while_depth):
        sc = self.scope
        name = _call_name(call)
        func = call.func
        recv = func.value if isinstance(func, ast.Attribute) else None

        # nested-def call sites: remember the held set
        if isinstance(func, ast.Name) and func.id in self.nested:
            self.nested_call_held.setdefault(func.id, []).append(
                frozenset(held))

        # same-scope method calls feed the closure + lock one-hop
        if recv is not None:
            attr = _self_attr(func)
            if attr and attr in sc.funcs:
                sc.calls.setdefault(self.fname, set()).add(attr)
                sc.method_call_sites.append(
                    (self.fname, attr, frozenset(held)))
        elif isinstance(func, ast.Name) and not sc.is_class \
                and func.id in sc.funcs:
            sc.calls.setdefault(self.fname, set()).add(func.id)
            sc.method_call_sites.append(
                (self.fname, func.id, frozenset(held)))

        # thread / submit / callback entry extraction
        if name in _THREAD_FACTORIES:
            self._thread_site(call)
        elif name in _SUBMIT_CALLS:
            for arg in list(call.args) + [k.value for k in
                                          call.keywords]:
                for kind, ref in _callable_refs(arg, sc.funcs):
                    if kind == "method" and ref in sc.funcs:
                        sc.entries.add(ref)
                    elif kind == "function" \
                            and ref in sc.module.module_funcs:
                        sc.module.module_scope.entries.add(ref)
        elif name in _CALLBACK_HOSTS:
            for arg in list(call.args) + [k.value for k in
                                          call.keywords]:
                for kind, ref in _callable_refs(arg, sc.funcs):
                    if kind == "method" and ref in sc.funcs:
                        sc.callbacks.add(ref)
                    elif kind in ("function", "maybe_function") \
                            and ref in sc.module.module_funcs:
                        sc.module.module_scope.callbacks.add(ref)
        elif name in _REGISTRY_CALLS:
            sc.registry_funcs.add(self.fname)
            for arg in call.args:
                attr = _self_attr(arg)
                if attr:
                    sc.registered_names.add("self.%s" % attr)
                elif isinstance(arg, ast.Name):
                    sc.registered_names.add(arg.id)

        # join targets for Q004
        if name == "join" and recv is not None:
            attr = _self_attr(recv)
            if attr:
                sc.join_targets.add("self.%s" % attr)
            elif isinstance(recv, ast.Name):
                sc.join_targets.add(recv.id)
                alias = self.name_alias.get(recv.id)
                if alias:
                    sc.join_targets.add(alias)

        # Q006: condition wait without a while re-check
        if name == "wait" and recv is not None:
            attr = _self_attr(recv)
            norm = sc.norm_lock(recv, self.locals)
            is_cond = False
            if attr is not None:
                fac = sc.lock_attrs.get(attr) or sc.lock_attrs.get(
                    sc.canon(attr))
                is_cond = (fac in _CONDITION_FACTORIES
                           or (fac is None
                               and _CONDISH_NAME.search(attr)))
            elif isinstance(recv, ast.Name):
                is_cond = bool(_CONDISH_NAME.search(recv.id))
            if is_cond:
                sc.waits.append((self.fname, call.lineno,
                                 norm or "?", while_depth))

        # Q003: blocking under lock
        if held:
            reason = _blocking_reason(
                call, held, lambda e: sc.norm_lock(e, self.locals))
            if reason is not None:
                sc.blocking.append((self.fname, call.lineno, reason,
                                    frozenset(held)))

        # Q001 write via mutator calls: self.buf.append(x)
        if name in _MUTATORS and recv is not None:
            attr = _self_attr(recv)
            if attr and sc.is_class and not sc.is_sync_attr(attr):
                sc.add_access(attr, "write", held, call.lineno,
                              self.fname)
            elif not attr:
                base = _base_name(recv)
                if base and base not in self.locals:
                    self._global_access(base, "write", held,
                                        call.lineno)

        self._expr(func.value if isinstance(func, ast.Attribute)
                   else None, held, while_depth)
        for arg in call.args:
            self._expr(arg, held, while_depth)
        for k in call.keywords:
            self._expr(k.value, held, while_depth)


# ----------------------------------------------------------------------
# module scan
# ----------------------------------------------------------------------
class _ModuleScan(object):
    def __init__(self, rel, tree):
        self.rel = rel
        self.stub = os.path.splitext(os.path.basename(rel))[0]
        self.tree = tree
        self.globals_ = set()         # module-level mutable names
        self.lock_globals = {}        # name -> factory
        self.global_alias = {}
        self.module_funcs = {}        # name -> node
        self.classes = []             # [_ScopeScan]
        self.global_accesses = {}     # name -> [_Access]
        self.module_scope = None

    def canon_global(self, name):
        seen = set()
        while name in self.global_alias and name not in seen:
            seen.add(name)
            name = self.global_alias[name]
        return name

    def scan(self):
        for node in self.tree.body:
            if isinstance(node, ast.Assign):
                fac = _is_sync_factory(node.value)
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        if fac:
                            self.lock_globals[tgt.id] = fac
                            if fac in _CONDITION_FACTORIES \
                                    and node.value.args \
                                    and isinstance(node.value.args[0],
                                                   ast.Name):
                                self.global_alias[tgt.id] = \
                                    node.value.args[0].id
                        else:
                            self.globals_.add(tgt.id)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                self.module_funcs[node.name] = node
        self.module_scope = _ScopeScan("<module>", self.module_funcs,
                                       False, self)
        scopes = [self.module_scope]
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef):
                methods = {}
                callback_class = False
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        methods[item.name] = item
                    elif isinstance(item, ast.Assign):
                        for tgt in item.targets:
                            if isinstance(tgt, ast.Name) \
                                    and tgt.id == "host_callback" \
                                    and isinstance(item.value,
                                                   ast.Constant) \
                                    and item.value.value is True:
                                callback_class = True
                sc = _ScopeScan(node.name, methods, True, self)
                sc.class_line = node.lineno
                if callback_class:
                    for m in ("forward", "backward"):
                        if m in methods:
                            sc.callbacks.add(m)
                self.classes.append(sc)
                scopes.append(sc)
        for sc in scopes:
            sc.scan_all()
        return scopes


# ----------------------------------------------------------------------
# findings
# ----------------------------------------------------------------------
_SEVERITY = {
    "MXL-Q001": "error", "MXL-Q002": "error", "MXL-Q003": "warning",
    "MXL-Q004": "warning", "MXL-Q005": "error", "MXL-Q006": "warning",
}


def _tag_label(tag):
    if tag == "main":
        return "the main/API path"
    kind, _, root = tag.partition(":")
    return "the %s path through %s()" % (
        "thread" if kind == "thread" else "host-callback", root)


def _scope_findings(sc, rel):
    """Q001/Q003/Q004/Q005/Q006 findings for one scope; yields
    (rule, line, qualname, message)."""
    ctx = sc.contexts()
    extra = sc.effective_locks()
    qual = (lambda m: "%s.%s" % (sc.name, m)) if sc.is_class \
        else (lambda m: m)

    def locks_of(a):
        return a.locks | extra.get(a.method, frozenset())

    # Q001 / Q005: shared state without a common lock
    reported = set()
    for attr, accs in sorted(sc.accesses.items()):
        if attr in reported:
            continue
        accs = [a for a in accs
                if a.method not in ("__init__", "__del__")]
        writes = [a for a in accs if a.kind == "write"]
        if not writes:
            continue
        hit = None
        for w in writes:
            for b in accs:
                if locks_of(w) & locks_of(b):
                    continue
                tw = ctx.get(w.method, set())
                tb = ctx.get(b.method, set())
                pairs = {(x, y) for x in tw for y in tb if x != y}
                if not pairs:
                    continue
                hit = (w, b, sorted(pairs)[0])
                break
            if hit:
                break
        if not hit:
            continue
        w, b, (tx, ty) = hit
        rule = "MXL-Q005" if (tx.startswith("callback")
                              or ty.startswith("callback")) \
            else "MXL-Q001"
        owner = "%s.%s" % (sc.name, attr) if sc.is_class else attr
        yield (rule, w.line, qual(w.method),
               "shared %s `%s` is written in %s() on %s (line %d) and "
               "%s in %s() on %s (line %d) with no common lock held"
               % ("attribute" if sc.is_class else "module global",
                  owner, w.method, _tag_label(tx), w.line,
                  b.kind, b.method, _tag_label(ty), b.line))
        reported.add(attr)

    # Q003: blocking call under lock (a helper's inherited locks from
    # effective_locks would be speculative for *blocking* — only flag
    # locks visibly held at the site)
    seen = set()
    for fname, line, what, locks in sc.blocking:
        key = (fname, line)
        if key in seen:
            continue
        seen.add(key)
        yield ("MXL-Q003", line, qual(fname),
               "%s while holding %s: the lock is pinned for the "
               "duration and every other thread needing it stalls"
               % (what, ", ".join(sorted(locks))))

    # Q004: unjoined/unregistered thread
    for ts in sc.thread_sites:
        if ts.registered:
            continue
        tlabel = ts.target[1] or "<dynamic>"
        yield ("MXL-Q004", ts.line, qual(ts.method),
               "thread targeting %s() is started without the io.py "
               "producer registry (_register_producer) and without a "
               "join path in this %s — it can outlive shutdown"
               % (tlabel, "class" if sc.is_class else "module"))

    # Q006: condition wait without while-predicate re-check
    for fname, line, norm, while_depth in sc.waits:
        if while_depth > 0:
            continue
        yield ("MXL-Q006", line, qual(fname),
               "Condition.wait() on %s outside a while-predicate "
               "re-check loop: spurious wakeups and stolen notifies "
               "break the invariant (use `while not pred: cv.wait()` "
               "or cv.wait_for(pred))" % norm)


def _module_global_findings(mod):
    """Q001/Q005 over module globals (accesses recorded from every
    scope in the file, contexts from the module function graph)."""
    sc = mod.module_scope
    ctx = sc.contexts()
    # fold in class-method accessors: context tags from their own class
    cls_ctx = {}
    for cls in mod.classes:
        cctx = cls.contexts()
        for m, tags in cctx.items():
            cls_ctx["%s.%s" % (cls.name, m)] = tags
    for name, accs in sorted(mod.global_accesses.items()):
        accs = [a for a in accs
                if not a.method.endswith(".__init__")]
        writes = [a for a in accs if a.kind == "write"]
        if not writes:
            continue
        hit = None
        for w in writes:
            for b in accs:
                if w.locks & b.locks:
                    continue
                tw = ctx.get(w.method) or cls_ctx.get(w.method) \
                    or {"main"}
                tb = ctx.get(b.method) or cls_ctx.get(b.method) \
                    or {"main"}
                pairs = {(x, y) for x in tw for y in tb if x != y}
                if not pairs:
                    continue
                hit = (w, b, sorted(pairs)[0])
                break
            if hit:
                break
        if not hit:
            continue
        w, b, (tx, ty) = hit
        rule = "MXL-Q005" if (tx.startswith("callback")
                              or ty.startswith("callback")) \
            else "MXL-Q001"
        yield (rule, w.line, w.method,
               "shared module global `%s` is written in %s() on %s "
               "(line %d) and %s in %s() on %s (line %d) with no "
               "common lock held"
               % (name, w.method, _tag_label(tx), w.line,
                  b.kind, b.method, _tag_label(ty), b.line))


def _lock_cycles(edges):
    """edges: {(A, B): (rel, qual, line)}.  Return cycles as lists of
    nodes (each cycle reported once, rotation-normalized)."""
    graph = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    cycles, seen = [], set()

    def dfs(node, path, on_path):
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_path:
                cyc = path[path.index(nxt):] + [nxt]
                nodes = cyc[:-1]
                pivot = min(range(len(nodes)),
                            key=lambda i: nodes[i])
                norm = tuple(nodes[pivot:] + nodes[:pivot])
                if norm not in seen:
                    seen.add(norm)
                    cycles.append(list(norm) + [norm[0]])
            elif nxt in graph and nxt not in visited_from_here:
                visited_from_here.add(nxt)
                path.append(nxt)
                on_path.add(nxt)
                dfs(nxt, path, on_path)
                on_path.discard(nxt)
                path.pop()

    for start in sorted(graph):
        visited_from_here = set()
        dfs(start, [start], {start})
    return cycles


def analyze_concurrency_paths(paths, root=None):
    """Run MXL-Q001..Q006 over .py files/dirs.  Returns a list of
    finding dicts: {rule, line, anchor, message[, severity]}."""
    root = root or os.getcwd()
    findings = []
    parsed = []
    for path in iter_py_files(paths):
        source, tree = _parse(path)
        rel = os.path.relpath(path, root)
        if source is None:
            findings.append({
                "rule": "MXL-Q001", "line": 0,
                "anchor": "%s:<file>" % rel, "severity": "warning",
                "message": "cannot parse %s for the concurrency lint: "
                           "%s" % (rel, tree)})
            continue
        parsed.append((rel, source, tree))

    lock_edges = {}        # (A, B) -> (rel, qual, line)
    per_file = []          # (rel, suppress, raw findings)
    for rel, source, tree in parsed:
        mod = _ModuleScan(rel, tree)
        scopes = mod.scan()
        raw = []
        for sc in scopes:
            # one-hop lock edges through same-scope calls
            for caller, callee, held in sc.method_call_sites:
                if not held:
                    continue
                for lock in sc.top_acquires.get(callee, ()):
                    for h in held:
                        if h != lock:
                            sc.acq_edges.append(
                                (h, lock, caller, 0))
            for (a, b, fname, line) in sc.acq_edges:
                qual = "%s.%s" % (sc.name, fname) if sc.is_class \
                    else fname
                lock_edges.setdefault((a, b), (rel, qual, line))
            raw.extend(_scope_findings(sc, rel))
        raw.extend(_module_global_findings(mod))
        per_file.append((rel, source, tree, raw))

    # Q002 cycles (package-wide graph)
    cycle_findings = {}    # rel -> [(rule, line, qual, message)]
    for cyc in _lock_cycles(lock_edges):
        sites = []
        for a, b in zip(cyc, cyc[1:]):
            sites.append((a, b) + lock_edges[(a, b)])
        rel0, qual0, line0 = sites[0][2], sites[0][3], sites[0][4]
        order = " -> ".join(cyc)
        detail = "; ".join("%s before %s at %s:%s" % (a, b, r, q)
                           for a, b, r, q, _l in sites)
        cycle_findings.setdefault(rel0, []).append(
            ("MXL-Q002", line0, qual0,
             "lock-order cycle %s: %s — threads taking these locks in "
             "opposing orders can deadlock" % (order, detail)))

    for rel, source, tree, raw in per_file:
        raw = raw + cycle_findings.get(rel, [])
        suppress = _suppressions(source)
        # def/class lines participate in suppression
        anchor_lines = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                for sub in ast.walk(node):
                    ln = getattr(sub, "lineno", None)
                    if ln is not None:
                        anchor_lines.setdefault(ln, set()).add(
                            node.lineno)
        for rule, line, qualname, message in raw:
            ids = suppress.get(line, set()) | \
                suppress.get(line - 1, set())
            for defline in anchor_lines.get(line, ()):
                ids |= suppress.get(defline, set()) | \
                    suppress.get(defline - 1, set())
            if "all" in ids or rule in ids:
                continue
            findings.append({
                "rule": rule, "line": line,
                "anchor": "%s:%s" % (rel, qualname),
                "message": "%s [in %s]" % (message, qualname)})
    findings.sort(key=lambda f: (f["anchor"], f["line"], f["rule"]))
    return findings


# ----------------------------------------------------------------------
# rule registration
# ----------------------------------------------------------------------
def _source_findings(ctx):
    if "concurrency" not in ctx.cache:
        ctx.cache["concurrency"] = \
            analyze_concurrency_paths(ctx.source_paths)
    return ctx.cache["concurrency"]


def _relay(ctx, rule):
    if not ctx.source_paths:
        return
    for f in _source_findings(ctx):
        if f["rule"] == rule:
            ctx.report(None, f["message"],
                       severity=f.get("severity"),
                       anchor=f["anchor"], line=f["line"])


@register_rule("MXL-Q001", "error",
               "shared attribute raced across threads without a "
               "common lock")
def thread_shared_race(ctx):
    """An attribute/global written on a thread-entry path and touched
    on another thread's path with no common lock held."""
    _relay(ctx, "MXL-Q001")


@register_rule("MXL-Q002", "error",
               "lock-order cycle (potential deadlock)")
def lock_order_cycle(ctx):
    """The package-wide acquired-while-held graph has a cycle."""
    _relay(ctx, "MXL-Q002")


@register_rule("MXL-Q003", "warning",
               "blocking call while holding a lock")
def blocking_under_lock(ctx):
    """queue/future/join/socket/subprocess/device-sync call executed
    with a lock held."""
    _relay(ctx, "MXL-Q003")


@register_rule("MXL-Q004", "warning",
               "thread started without registry or join path")
def unjoined_thread(ctx):
    """Thread outside the io.py producer registry with no join."""
    _relay(ctx, "MXL-Q004")


@register_rule("MXL-Q005", "error",
               "host-callback mutates step-path state unsynchronized")
def callback_context_violation(ctx):
    """A host-callback body writes state the step path also touches
    with no common lock — the PR-13 torch bridge bug shape."""
    _relay(ctx, "MXL-Q005")


@register_rule("MXL-Q006", "warning",
               "condition wait without predicate re-check loop")
def wait_without_recheck(ctx):
    """Condition.wait() not wrapped in a while-predicate loop."""
    _relay(ctx, "MXL-Q006")
