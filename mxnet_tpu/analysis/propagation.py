"""SPMD sharding propagation (rule family MXL-P).

The scaling-book failure mode this pass catches: you annotate a mesh and
per-name PartitionSpecs, XLA's SPMD partitioner silently *makes it work*
— inserting all-gathers and reshards wherever the annotated layouts
disagree — and the first sign of trouble is an ICI-bound profile three
hours into a run.  The reference had a crude analog (kvstore picked one
reduction layout per key and you found out at runtime); here the graph
is static, so the layout algebra can run at bind/lint time.

The pass seeds every argument with the PartitionSpec the trainer would
bind (``parallel.sharding.named_pspecs`` — explicit ShardingRules first,
then the default megatron-style policy) and pushes specs forward through
every op via its transfer rule (``ops.registry.sharding_transfer``,
registered alongside the lowering metadata).  Diffing each op's
*required* input layout against what actually *arrives* classifies every
implicit collective XLA would insert:

- MXL-P001  error    irreconcilable specs on one dim (different mesh
                     axes): a forced reshard (all-to-all) — almost
                     always an annotation bug;
- MXL-P002  warning  sharded value consumed replicated: an implicit
                     all-gather, with the ICI bytes it moves;
- MXL-P003  info     parameter the tp policy wanted to shard but
                     couldn't (no divisible dim): degraded to
                     replicated (from ``named_pspecs`` notes);
- MXL-P004  info     sharded contraction: XLA inserts the matching
                     psum (expected for row-parallel layers — listed so
                     the cost report is complete).

Byte estimates use the standard ring costs: all-gather of a tensor with
global size B over an axis of k devices moves B·(k-1)/k per device;
psum (reduce-scatter + all-gather) moves 2·B·(k-1)/k.

``comm_report`` aggregates the events into the per-graph communication
table ``tools/mxlint.py --mesh ...`` prints.
"""
from __future__ import annotations

import numpy as _np

from ..ops.registry import sharding_transfer
from .core import register_rule
from .shapes import _propagate_types

__all__ = ["propagate", "comm_report", "fmt_bytes"]


# ----------------------------------------------------------------------
# shared cached graph facts
# ----------------------------------------------------------------------
def edge_shapes(ctx):
    """Per-edge shape map {(id(node), out_idx): tuple} — the same
    fixpoint as ``Symbol._infer_shape_impl`` but non-throwing (a shape
    contradiction is MXL-S002's finding; this pass just skips the node)
    and keeping every interior edge.  Cached on the context."""
    if "edge_shapes" in ctx.cache:
        return ctx.cache["edge_shapes"]
    from ..dparam import parse_tuple
    shapes = {}
    for node in ctx.topo:
        if node.is_variable:
            if node.name in ctx.shapes:
                shapes[(id(node), 0)] = tuple(ctx.shapes[node.name])
            elif "__shape__" in node.attrs:
                try:
                    shapes[(id(node), 0)] = parse_tuple(
                        node.attrs["__shape__"])
                except Exception:
                    pass
    while True:
        progress = False
        for node in ctx.topo:
            if node.is_variable:
                continue
            in_shapes = [shapes.get((id(c), ci)) for c, ci in node.inputs]
            try:
                full_in, outs, _aux = node.op.infer_shape(in_shapes)
            except Exception:   # incomplete/contradictory: not our finding
                continue
            for (c, ci), s in zip(node.inputs, full_in):
                key = (id(c), ci)
                if s is not None and shapes.get(key) is None:
                    shapes[key] = tuple(s)
                    progress = True
            for i, s in enumerate(outs):
                key = (id(node), i)
                if s is not None and shapes.get(key) is None:
                    shapes[key] = tuple(s)
                    progress = True
        if not progress:
            break
    ctx.cache["edge_shapes"] = shapes
    return shapes


def edge_types(ctx):
    """Per-edge dtype map (cached wrapper over the MXL-T walk)."""
    if "edge_types" not in ctx.cache:
        types, _failed = _propagate_types(ctx)
        ctx.cache["edge_types"] = types
    return ctx.cache["edge_types"]


# ----------------------------------------------------------------------
# spec algebra
# ----------------------------------------------------------------------
def _normalize(spec, rank):
    """PartitionSpec / loose tuple -> normalized: ``rank`` entries, each
    a tuple of mesh-axis names (() = replicated on that dim)."""
    if spec is None:
        return ((),) * rank
    out = []
    for entry in tuple(spec)[:rank]:
        if entry is None:
            out.append(())
        elif isinstance(entry, (tuple, list)):
            out.append(tuple(entry))
        else:
            out.append((entry,))
    out.extend([()] * (rank - len(out)))
    return tuple(out)


def _axis_size(axes, mesh_shape):
    k = 1
    for a in axes or ():
        k *= int(mesh_shape.get(a, 1))
    return k


def _edge_bytes(shape, dtype):
    return int(_np.prod(shape, dtype=_np.int64)) * \
        _np.dtype(dtype or _np.float32).itemsize


def fmt_bytes(n):
    """Human byte count for reports (1024-based, one decimal)."""
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return ("%.1f%s" % (n, unit)) if unit != "B" \
                else ("%d%s" % (int(n), unit))
        n /= 1024.0


# ----------------------------------------------------------------------
# the propagation pass proper
# ----------------------------------------------------------------------
def propagate(ctx):
    """Run forward sharding propagation once per context (cached).

    Returns ``{"specs", "events", "seed_notes", "seeds", "ok"}``:
    per-edge normalized specs, the implicit-collective event list, the
    seeding-degradation notes, the per-argument seed specs, and whether
    every node could be processed (unknown shapes make it partial).
    """
    if "propagation" in ctx.cache:
        return ctx.cache["propagation"]
    result = {"specs": {}, "events": [], "seed_notes": [], "seeds": {},
              "ok": False}
    ctx.cache["propagation"] = result
    if ctx.mesh is None or ctx.symbol is None:
        return result
    from ..parallel.sharding import named_pspecs
    mesh_shape = dict(ctx.mesh.shape)
    shapes = edge_shapes(ctx)
    types = edge_types(ctx)
    specs = result["specs"]
    events = result["events"]

    named_shapes = {n.name: shapes.get((id(n), 0))
                    for n in ctx.variables()}
    notes = []
    by_name = named_pspecs(named_shapes, ctx.mesh,
                           rules=ctx.sharding_rules,
                           data_names=ctx.data_names,
                           label_names=ctx.label_names, notes=notes)
    result["seed_notes"] = notes
    for node in ctx.variables():
        shape = named_shapes.get(node.name)
        if shape is None:
            continue
        spec = _normalize(by_name.get(node.name), len(shape))
        specs[(id(node), 0)] = spec
        result["seeds"][node.name] = spec

    complete = True
    for node in ctx.topo:
        if node.is_variable:
            continue
        in_keys = [(id(c), ci) for c, ci in node.inputs]
        in_shapes = [shapes.get(k) for k in in_keys]
        out_shapes = [shapes.get((id(node), i))
                      for i in range(node.num_outputs)]
        if any(s is None for s in in_shapes) or \
                any(s is None for s in out_shapes):
            complete = False
            for i, s in enumerate(out_shapes):
                if s is not None:
                    specs[(id(node), i)] = ((),) * len(s)
            continue
        in_specs = [specs.get(k) or ((),) * len(s)
                    for k, s in zip(in_keys, in_shapes)]
        try:
            xfer = sharding_transfer(node.op, in_specs, in_shapes,
                                     out_shapes, mesh_shape) or {}
        except Exception:   # a broken rule must not kill the whole pass
            complete = False
            xfer = {}
        arg_names = node.op.list_arguments()

        for idx, req in enumerate(xfer.get("in") or ()):
            if req is None or idx >= len(in_specs):
                continue
            actual = in_specs[idx]
            req = _normalize(req, len(in_shapes[idx]))
            gbytes = _edge_bytes(in_shapes[idx], types.get(in_keys[idx]))
            aname = arg_names[idx] if idx < len(arg_names) else "in%d" % idx
            src = node.inputs[idx][0].name
            for d in range(len(actual)):
                act_d, req_d = actual[d], req[d]
                if act_d == req_d or not act_d:
                    continue        # match, or free reslice of replicated
                k = _axis_size(act_d, mesh_shape)
                if not req_d:
                    events.append({
                        "kind": "gather", "node": node, "arg": idx,
                        "axes": act_d,
                        "bytes": gbytes * (k - 1) // k,
                        "message":
                            "input %r (%s) arrives sharded over %s on dim "
                            "%d but %s consumes it replicated: XLA inserts "
                            "an implicit all-gather moving ~%s per device "
                            "over ICI" % (
                                aname, src, "+".join(act_d), d,
                                node.op.op_name,
                                fmt_bytes(gbytes * (k - 1) // k))})
                else:
                    events.append({
                        "kind": "reshard", "node": node, "arg": idx,
                        "axes": tuple(act_d) + tuple(req_d),
                        "bytes": gbytes * (k - 1) // k,
                        "message":
                            "input %r (%s) arrives sharded over %s on dim "
                            "%d but %s requires %s there: XLA inserts a "
                            "forced reshard (all-to-all) moving ~%s per "
                            "device over ICI — almost always a sharding-"
                            "rule conflict" % (
                                aname, src, "+".join(act_d), d,
                                node.op.op_name, "+".join(req_d),
                                fmt_bytes(gbytes * (k - 1) // k))})

        for axes, reason in (xfer.get("reduce") or {}).items():
            axes = tuple(axes)
            k = _axis_size(axes, mesh_shape)
            gbytes = _edge_bytes(out_shapes[0],
                                 types.get((id(node), 0)))
            events.append({
                "kind": "reduce", "node": node, "arg": None, "axes": axes,
                "bytes": 2 * gbytes * (k - 1) // k,
                "message": "%s: XLA inserts a psum over %s moving ~%s per "
                           "device" % (reason, "+".join(axes),
                                       fmt_bytes(2 * gbytes * (k - 1) // k))})

        for note in xfer.get("notes") or ():
            idx = note.get("arg", 0)
            axes = tuple(note.get("axes") or ())
            k = _axis_size(axes, mesh_shape)
            gbytes = _edge_bytes(in_shapes[idx], types.get(in_keys[idx])) \
                if idx < len(in_shapes) else 0
            events.append({
                "kind": note.get("kind", "note"), "node": node, "arg": idx,
                "axes": axes, "bytes": gbytes * (k - 1) // k,
                "message": "%s (~%s per device over ICI)"
                           % (note.get("message", ""),
                              fmt_bytes(gbytes * (k - 1) // k))})

        for i, ospec in enumerate(xfer.get("out") or ()):
            if i < len(out_shapes) and out_shapes[i] is not None:
                specs[(id(node), i)] = _normalize(ospec, len(out_shapes[i]))
        for i, s in enumerate(out_shapes):
            if (id(node), i) not in specs and s is not None:
                specs[(id(node), i)] = ((),) * len(s)

    result["ok"] = complete
    return result


def comm_report(ctx):
    """Aggregate the propagation events into the per-graph communication
    cost table: total ICI bytes per device and a per-kind breakdown.
    Serializable (node objects become names) for the CLI's json mode."""
    prop = propagate(ctx)
    by_kind = {}
    total = 0
    rows = []
    for ev in prop["events"]:
        entry = by_kind.setdefault(ev["kind"], {"count": 0, "bytes": 0})
        entry["count"] += 1
        entry["bytes"] += ev["bytes"]
        total += ev["bytes"]
        rows.append({"kind": ev["kind"],
                     "node": getattr(ev["node"], "name", ev["node"]),
                     "axes": list(ev["axes"]), "bytes": ev["bytes"],
                     "message": ev["message"]})
    return {"total_bytes": total, "by_kind": by_kind, "events": rows,
            "complete": prop["ok"],
            "degraded": [{"name": n, "message": m}
                         for n, m in prop["seed_notes"]]}


# ----------------------------------------------------------------------
# rules
# ----------------------------------------------------------------------
@register_rule("MXL-P001", "error",
               "sharding conflict forces an implicit reshard")
def sharding_conflict(ctx):
    """Two different mesh axes claim one dim at an op input."""
    if ctx.mesh is None:
        return
    for ev in propagate(ctx)["events"]:
        if ev["kind"] == "reshard":
            ctx.report(ev["node"], ev["message"])


@register_rule("MXL-P002", "warning",
               "sharded value consumed replicated: implicit all-gather")
def implicit_gather(ctx):
    """A sharded tensor flows into an op that needs it whole."""
    if ctx.mesh is None:
        return
    for ev in propagate(ctx)["events"]:
        if ev["kind"] == "gather":
            ctx.report(ev["node"], ev["message"])


@register_rule("MXL-P003", "info",
               "parameter degraded to replicated (no divisible dim)")
def sharding_degraded(ctx):
    """The default tp policy wanted to shard but no dim divides."""
    if ctx.mesh is None:
        return
    for name, msg in propagate(ctx)["seed_notes"]:
        ctx.report(name, msg)


@register_rule("MXL-P004", "info",
               "sharded contraction: XLA inserts the matching psum")
def sharded_contraction(ctx):
    """Expected collectives (row-parallel matmuls, vocab-sharded
    embeddings) — reported at info so the cost table is complete."""
    if ctx.mesh is None:
        return
    for ev in propagate(ctx)["events"]:
        if ev["kind"] == "reduce":
            ctx.report(ev["node"], ev["message"])
