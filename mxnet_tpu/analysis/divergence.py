"""Rank-divergence dataflow lint over the framework's own source
(rule family MXL-D004..D006).

Every distributed bug that reached review in this repo was a
*rank-divergence* bug — a pid-dependent checkpoint scratch path, a
per-rank barrier-implementation probe that could split the pod, a
device-0-only grad-norm sentinel — and none of the graph-level MXL
families can see them, because they live in the Python runtime around
the graph (trainer loops, kvstore, resilience/, observability), not in
the graph itself.  This pass is a lightweight intraprocedural taint
analysis over that Python source:

- **Sources** (values that may differ across ranks): ``os.getpid``,
  wall/monotonic clocks, unseeded ``random``/``np.random``, hostname,
  ``uuid1/uuid4``, per-process temp paths, ``jax.process_index()`` /
  names and attributes called ``rank``, per-process device views
  (``.addressable_data(...)``), and anything assigned on an exception
  edge (whether an exception fires is rank-local).
- **Sinks**: coordinated checkpoint paths (``ocp_save`` & friends —
  MXL-D004, error), collective call conditions / loop trip counts /
  early exits ahead of a collective (MXL-D005, error), and exception
  edges that can exit between paired collectives or swallow a failing
  collective on one rank (MXL-D006, warning).

Two markers make intent explicit (docs/graph_lint.md):

- ``@collective_seam`` (``mxnet_tpu.base.collective_seam``) declares a
  function a cluster-wide rendezvous/agreement protocol: calls to it are
  collective sinks, its *return value* is certified rank-uniform (the
  protocol's whole point — e.g. ``kvstore._decide_csum_path`` publishes
  rank 0's verdict through the coordination KV), and the intentional
  rank-asymmetry inside its body is exempt from MXL-D005.
- ``# mxl: rank-divergent-ok`` (optionally ``(MXL-D005,...)``) on the
  finding line, the line above it, or the enclosing ``def`` line
  suppresses matching findings — the comment IS the review record for
  why the divergence is safe.

Findings carry a stable ``file:qualname`` anchor (plus the volatile
line for CI annotations) so ``mxlint --baseline`` records survive
unrelated edits.  The analysis never imports or executes the scanned
files — pure ``ast``, so fixtures snapshotting old bugs are safe to
keep in-tree.

Deliberately NOT tainted: ``jax.process_count()`` (uniform),
filesystem predicates and listings (shared-filesystem reads are how
``latest_step`` legitimately agrees), and coordination-KV reads
(``blocking_key_value_get`` is how verdicts are *shared*, not where
they diverge).
"""
from __future__ import annotations

import ast
import os
import re

from .core import register_rule

__all__ = ["collective_seam", "analyze_source_paths", "iter_py_files",
           "SUPPRESS_RE"]

# re-exported so `from mxnet_tpu.analysis.divergence import
# collective_seam` works; the canonical home is base.py (a leaf module
# the annotated subsystems can import without cycles)
from ..base import collective_seam  # noqa: E402,F401


# ----------------------------------------------------------------------
# vocabulary
# ----------------------------------------------------------------------
# terminal call names whose result differs across ranks
_SOURCE_CALLS = {
    "getpid": "os.getpid()",
    "getppid": "os.getppid()",
    "gethostname": "the hostname",
    "mkdtemp": "a per-process temp path",
    "mkstemp": "a per-process temp path",
    "mktemp": "a per-process temp path",
    "NamedTemporaryFile": "a per-process temp file",
    "TemporaryDirectory": "a per-process temp path",
    "uuid1": "uuid1()",
    "uuid4": "uuid4()",
    "process_index": "jax.process_index()",
    "addressable_data": "a per-process device shard "
                        "(.addressable_data: this rank's local view, "
                        "not the global value)",
}
# clock calls: unqualified names that are only divergent when they hang
# off a time-ish module (`time.time`, `_time.monotonic`, ...)
_CLOCK_CALLS = {"time", "monotonic", "perf_counter", "time_ns", "clock"}
# names/attributes whose *value* is the rank
_RANK_NAMES = {"rank", "process_index", "worker_rank", "local_rank"}

# collective sinks: every rank must reach these together.  Terminal call
# name matching (`client.wait_at_barrier` -> `wait_at_barrier`).
_COLLECTIVE_CALLS = {
    "global_barrier", "wait_at_barrier", "sync_global_devices",
    "barrier", "_barrier", "psum", "pmean", "pmax", "pmin",
    "all_gather", "all_reduce", "allreduce", "_allreduce",
    "_allreduce_dist", "_collective_sum", "_kv_allreduce",
    "ppermute", "all_to_all", "pbroadcast",
}
# coordinated-path sinks: multi-host protocols that hand every rank the
# SAME path/target (orbax coordinated saves strand shards otherwise)
_COORDINATED_CALLS = {
    "ocp_save": "the coordinated multi-host checkpoint save",
    "ocp_restore": "the coordinated multi-host checkpoint restore",
    "save_checkpoint_versioned": "the versioned checkpoint protocol",
    "auto_resume": "the coordinated checkpoint resume",
    "CheckpointManager": "the checkpoint manager's shared directory",
    "save_checkpoint": "the classic checkpoint writer",
    "load_checkpoint": "the classic checkpoint reader",
}

SUPPRESS_RE = re.compile(
    r"#\s*mxl:\s*rank-divergent-ok(?:\s*\(([^)]*)\))?")

_SEAM_DECORATOR = "collective_seam"


def iter_py_files(paths):
    """Expand files/directories into a sorted list of .py files."""
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__"
                                 and not d.startswith("."))
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
        elif p.endswith(".py"):
            out.append(p)
    seen, uniq = set(), []
    for p in out:
        if p not in seen:
            seen.add(p)
            uniq.append(p)
    return uniq


# ----------------------------------------------------------------------
# AST helpers
# ----------------------------------------------------------------------
def _dotted(node):
    """Best-effort dotted name of an expression (``a.b.c``), else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    if parts:
        return ".".join(reversed(parts))
    return None


def _call_name(call):
    """Terminal name of a call's callee (``client.wait_at_barrier`` ->
    ``wait_at_barrier``)."""
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _decorator_names(fn):
    return {d for d in
            (_call_name(dec) if isinstance(dec, ast.Call)
             else (dec.attr if isinstance(dec, ast.Attribute)
                   else (dec.id if isinstance(dec, ast.Name) else None))
             for dec in fn.decorator_list)
            if d}


def _suppressions(source):
    """line -> set of rule ids (or {'all'}) from rank-divergent-ok
    marker comments."""
    out = {}
    for i, line in enumerate(source.splitlines(), 1):
        m = SUPPRESS_RE.search(line)
        if not m:
            continue
        ids = {s.strip() for s in (m.group(1) or "").split(",")
               if s.strip()}
        out[i] = ids or {"all"}
    return out


class _GlobalInfo(object):
    """Cross-file facts the per-function pass consumes."""

    def __init__(self):
        self.seams = set(_COLLECTIVE_CALLS)   # seam fns are sinks too
        self.seam_defs = set()                # names defined @collective_seam
        self.divergent_fns = {}               # fn name -> taint reason


# ----------------------------------------------------------------------
# the per-function taint + findings engine
# ----------------------------------------------------------------------
class _FunctionPass(object):
    """Intraprocedural, flow-insensitive taint over one function (nested
    defs walked inline: the closure style here invokes them in place)."""

    def __init__(self, fn_node, qualname, ginfo, is_seam):
        self.fn = fn_node
        self.qualname = qualname
        self.ginfo = ginfo
        self.is_seam = is_seam
        self.tainted = {}          # name -> human reason
        self.findings = []         # (rule, line, message)
        self.collectives = []      # (line, call name)
        self.exits = []            # (line, kind, taint reason|None)
        self.return_taint = None   # reason when a return value is tainted

    # -- taint of an expression -------------------------------------------
    def taint(self, node):
        """Reason string when ``node`` may differ across ranks, else
        None."""
        if node is None:
            return None
        if isinstance(node, ast.Name):
            if node.id in self.tainted:
                return self.tainted[node.id]
            if node.id in _RANK_NAMES:
                return "the rank (%r)" % node.id
            return None
        if isinstance(node, ast.Attribute):
            if node.attr in _RANK_NAMES:
                return "the rank (.%s)" % node.attr
            return self.taint(node.value)
        if isinstance(node, ast.Call):
            return self._call_taint(node)
        if isinstance(node, ast.BoolOp):
            for v in node.values:
                r = self.taint(v)
                if r:
                    return r
            return None
        if isinstance(node, ast.BinOp):
            return self.taint(node.left) or self.taint(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.taint(node.operand)
        if isinstance(node, ast.Compare):
            r = self.taint(node.left)
            if r:
                return r
            for c in node.comparators:
                r = self.taint(c)
                if r:
                    return r
            return None
        if isinstance(node, ast.IfExp):
            return (self.taint(node.test) or self.taint(node.body)
                    or self.taint(node.orelse))
        if isinstance(node, ast.Subscript):
            return self.taint(node.value) or self.taint(node.slice)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            for e in node.elts:
                r = self.taint(e)
                if r:
                    return r
            return None
        if isinstance(node, ast.Dict):
            for e in list(node.keys) + list(node.values):
                r = self.taint(e)
                if r:
                    return r
            return None
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                r = self.taint(v)
                if r:
                    return r
            return None
        if isinstance(node, ast.FormattedValue):
            return self.taint(node.value)
        if isinstance(node, ast.Starred):
            return self.taint(node.value)
        return None

    def _call_taint(self, call):
        name = _call_name(call)
        dotted = _dotted(call.func) or (name or "")
        segs = dotted.split(".")
        if name in _SOURCE_CALLS:
            return _SOURCE_CALLS[name]
        if name in _CLOCK_CALLS and len(segs) > 1 and \
                "time" in segs[-2].lower():
            return "the local clock (%s)" % dotted
        if name in ("now", "utcnow") and any(
                "datetime" in s.lower() for s in segs[:-1]):
            return "the local clock (%s)" % dotted
        if any(s in ("random",) for s in segs[:-1]) or name == "random":
            # unseeded RNG state diverges; explicitly-seeded constructors
            # (RandomState(7), default_rng(seed)) are rank-uniform
            if name in ("RandomState", "default_rng", "Generator",
                        "PRNGKey", "seed") and (call.args or
                                                call.keywords):
                pass
            else:
                return "unseeded random state (%s)" % dotted
        if name in self.ginfo.seam_defs:
            return None     # seam contract: return is rank-uniform
        if name in self.ginfo.divergent_fns:
            return "%s() (returns %s)" % (
                name, self.ginfo.divergent_fns[name])
        # unknown call: propagates taint from its operands (str(rank),
        # os.path.join(root, piddir), "%s" % rank, tainted.method())
        if isinstance(call.func, ast.Attribute):
            r = self.taint(call.func.value)
            if r:
                return r
        for a in call.args:
            r = self.taint(a)
            if r:
                return r
        for k in call.keywords:
            r = self.taint(k.value)
            if r:
                return r
        return None

    # -- phase 1: fixpoint taint collection --------------------------------
    def collect_taint(self):
        args = self.fn.args if hasattr(self.fn, "args") else None
        if args is not None:
            for a in (list(args.posonlyargs) + list(args.args)
                      + list(args.kwonlyargs)):
                if a.arg in _RANK_NAMES:
                    self.tainted[a.arg] = "the rank parameter %r" % a.arg
        for _ in range(8):
            changed = False
            for node in ast.walk(self.fn):
                targets, value, reason = (), None, None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) and node.value:
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.AugAssign):
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.NamedExpr):
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.For):
                    targets, value = [node.target], node.iter
                elif isinstance(node, ast.withitem) and \
                        node.optional_vars is not None:
                    targets, value = [node.optional_vars], \
                        node.context_expr
                elif isinstance(node, ast.ExceptHandler):
                    # whether an exception fired is rank-local: the
                    # bound name and everything assigned in the handler
                    # body is divergent
                    reason = ("an exception edge (whether the exception "
                              "fires is rank-local)")
                    names = set()
                    if node.name:
                        names.add(node.name)
                    for sub in ast.walk(node):
                        if isinstance(sub, (ast.Assign, ast.AugAssign)):
                            tg = sub.targets if isinstance(
                                sub, ast.Assign) else [sub.target]
                            for t in tg:
                                names.update(self._target_names(t))
                    for n in names:
                        if n not in self.tainted:
                            self.tainted[n] = reason
                            changed = True
                    continue
                else:
                    continue
                reason = self.taint(value)
                if not reason:
                    continue
                for t in targets:
                    for n in self._target_names(t):
                        if n not in self.tainted:
                            self.tainted[n] = reason
                            changed = True
            if not changed:
                break
        # a function returning a tainted expression spreads divergence
        # to its callers (``_is_coordinator`` returning
        # ``jax.process_index() == 0``)
        for node in ast.walk(self.fn):
            if isinstance(node, ast.Return) and node.value is not None:
                r = self.taint(node.value)
                if r:
                    self.return_taint = r
                    break

    @staticmethod
    def _target_names(t):
        if isinstance(t, ast.Name):
            return [t.id]
        if isinstance(t, (ast.Tuple, ast.List)):
            out = []
            for e in t.elts:
                out.extend(_FunctionPass._target_names(e))
            return out
        if isinstance(t, ast.Starred):
            return _FunctionPass._target_names(t.value)
        if isinstance(t, ast.Subscript):
            # _STATE["flag"] = ...  taints the container name
            return _FunctionPass._target_names(t.value)
        return []

    # -- phase 2: findings over the statement tree -------------------------
    def run(self):
        self.collect_taint()
        body = self.fn.body if hasattr(self.fn, "body") else []
        self._visit_stmts(body, conds=[], swallow=None)
        self._pair_exits()
        return self.findings

    def _visit_stmts(self, stmts, conds, swallow):
        for stmt in stmts:
            self._visit_stmt(stmt, conds, swallow)

    def _visit_stmt(self, stmt, conds, swallow):
        if isinstance(stmt, ast.If):
            self._scan_expr(stmt.test, conds, swallow)
            reason = self.taint(stmt.test)
            inner = conds + ([(stmt.test, reason)] if reason else [])
            self._visit_stmts(stmt.body, inner, swallow)
            self._visit_stmts(stmt.orelse, inner, swallow)
        elif isinstance(stmt, ast.While):
            self._scan_expr(stmt.test, conds, swallow)
            reason = self.taint(stmt.test)
            inner = conds + ([(stmt.test, reason)] if reason else [])
            self._visit_stmts(stmt.body, inner, swallow)
            self._visit_stmts(stmt.orelse, inner, swallow)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._scan_expr(stmt.iter, conds, swallow)
            reason = self.taint(stmt.iter)
            if reason:
                reason = ("a loop over a rank-divergent iterable "
                          "(trip count tainted by %s)" % reason)
            inner = conds + ([(stmt.iter, reason)] if reason else [])
            self._visit_stmts(stmt.body, inner, swallow)
            self._visit_stmts(stmt.orelse, inner, swallow)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._scan_expr(item.context_expr, conds, swallow)
            self._visit_stmts(stmt.body, conds, swallow)
        elif isinstance(stmt, ast.Try):
            swallows = self._swallowing_handler(stmt)
            inner_swallow = (stmt, swallows) if swallows else swallow
            self._visit_stmts(stmt.body, conds, inner_swallow)
            self._visit_stmts(stmt.orelse, conds, inner_swallow)
            for h in stmt.handlers:
                self._visit_stmts(h.body, conds, swallow)
            self._visit_stmts(stmt.finalbody, conds, swallow)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested defs analyzed inline: the closures here
            # (kvstore.barrier's _sync, heartbeat's _beat) run in place
            self._visit_stmts(stmt.body, conds, swallow)
        elif isinstance(stmt, ast.Return):
            self._scan_expr(stmt.value, conds, swallow)
            reason = next((r for _e, r in conds if r), None)
            self.exits.append((stmt.lineno, "return", reason))
        elif isinstance(stmt, ast.Raise):
            self._scan_expr(stmt.exc, conds, swallow)
            reason = next((r for _e, r in conds if r), None)
            self.exits.append((stmt.lineno, "raise", reason))
        elif isinstance(stmt, ast.ClassDef):
            pass    # handled by the file walker
        else:
            self._scan_expr(stmt, conds, swallow)

    @staticmethod
    def _swallowing_handler(try_stmt):
        """True when some handler continues past the exception (no
        re-raise anywhere in its body)."""
        for h in try_stmt.handlers:
            if not any(isinstance(n, ast.Raise) for n in ast.walk(h)):
                return True
        return False

    def _scan_expr(self, node, conds, swallow):
        """Find sink calls inside one statement/expression subtree
        (compound statements dispatch their bodies separately)."""
        if node is None:
            return
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            name = _call_name(sub)
            if name in self.ginfo.seams:
                self._collective_hit(sub, name, conds, swallow)
            if name in _COORDINATED_CALLS:
                self._coordinated_hit(sub, name)

    def _collective_hit(self, call, name, conds, swallow):
        line = call.lineno
        self.collectives.append((line, name))
        tainted = [(e, r) for e, r in conds if r]
        if tainted and not self.is_seam:
            _expr, reason = tainted[0]
            self.findings.append((
                "MXL-D005", line,
                "collective %s() is gated on rank-divergent control "
                "flow (condition tainted by %s): ranks that take the "
                "other path never join it and the pod deadlocks"
                % (name, reason)))
        if swallow is not None:
            try_stmt, _ = swallow
            self.findings.append((
                "MXL-D006", line,
                "collective %s() runs inside a try whose handler "
                "swallows the exception: a rank where it raises "
                "continues past the rendezvous while its peers are "
                "still waiting in it (unbalanced collective on an "
                "exception edge)" % name))

    def _coordinated_hit(self, call, name):
        for arg in list(call.args) + [k.value for k in call.keywords]:
            reason = self.taint(arg)
            if reason:
                self.findings.append((
                    "MXL-D004", call.lineno,
                    "rank-divergent value (tainted by %s) flows into "
                    "%s() — %s needs the IDENTICAL argument on every "
                    "rank, or shards land in per-rank locations the "
                    "commit protocol never sees"
                    % (reason, name, _COORDINATED_CALLS[name])))
                return

    def _pair_exits(self):
        """Rank-divergent early exits vs. the function's collectives:
        an exit BEFORE any collective means some ranks never join it
        (D005); an exit BETWEEN two collectives leaves the pair
        unbalanced (D006)."""
        if not self.collectives:
            return
        lines = sorted(l for l, _ in self.collectives)
        by_line = dict(self.collectives)
        for line, kind, reason in self.exits:
            if reason is None or line in by_line:
                # an exit on a collective's own line (`return psum(x)`)
                # already reported through the call-site check
                continue
            later = [l for l in lines if l > line]
            earlier = [l for l in lines if l < line]
            if not later:
                continue
            nxt = by_line[later[0]]
            if earlier and not self.is_seam:
                self.findings.append((
                    "MXL-D006", line,
                    "rank-divergent %s (condition tainted by %s) exits "
                    "between paired collectives (%s() behind it, %s() "
                    "ahead): ranks taking it complete the first "
                    "rendezvous but never the second"
                    % (kind, reason, by_line[earlier[-1]], nxt)))
            elif not self.is_seam:
                self.findings.append((
                    "MXL-D005", line,
                    "rank-divergent early %s (condition tainted by %s) "
                    "ahead of collective %s(): ranks taking it never "
                    "join the rendezvous — decide skip-verdicts "
                    "globally (accumulate every shard / publish rank "
                    "0's verdict), not from rank-local state"
                    % (kind, reason, nxt)))


# ----------------------------------------------------------------------
# file + file-set drivers
# ----------------------------------------------------------------------
def _iter_functions(tree):
    """Yield (qualname, node, decorators) for every top-level function
    and method; module-level statements come back as ('<module>',
    pseudo-fn) when any exist."""
    out = []

    def _walk(nodes, prefix):
        for n in nodes:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append((prefix + n.name, n, _decorator_names(n)))
            elif isinstance(n, ast.ClassDef):
                _walk(n.body, prefix + n.name + ".")

    _walk(tree.body, "")
    loose = [n for n in tree.body
             if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef, ast.Import,
                                   ast.ImportFrom))]
    if loose:
        pseudo = ast.Module(body=loose, type_ignores=[])
        out.append(("<module>", pseudo, set()))
    return out


def _parse(path):
    try:
        with open(path, "r") as f:
            source = f.read()
        return source, ast.parse(source, filename=path)
    except (OSError, SyntaxError) as exc:
        return None, exc


def analyze_source_paths(paths, root=None):
    """Run the MXL-D004..006 pass over ``paths`` (.py files and/or
    directories).  Returns finding dicts ``{"rule", "line", "anchor",
    "message"}`` with ``anchor = relpath:qualname`` (stable across
    unrelated edits; the line is display-only).

    Two phases: the first scans every file for ``@collective_seam``
    definitions and for functions returning rank-divergent values
    (iterated so single-hop indirection like ``_is_coordinator`` is
    seen everywhere); the second runs the taint/findings engine with
    the whole-set vocabulary.
    """
    root = root or os.getcwd()
    files = iter_py_files(paths)
    parsed = []         # (relpath, source, tree)
    findings = []
    for path in files:
        source, tree = _parse(path)
        rel = os.path.relpath(path, root)
        if source is None:
            findings.append({
                "rule": "MXL-D004", "line": 0,
                "anchor": "%s:<file>" % rel,
                "severity": "warning",
                "message": "cannot parse %s for the distributed lint: "
                           "%s" % (rel, tree)})
            continue
        parsed.append((rel, source, tree))

    ginfo = _GlobalInfo()
    for _rel, _src, tree in parsed:
        for qual, fn, decs in _iter_functions(tree):
            if _SEAM_DECORATOR in decs:
                name = qual.rsplit(".", 1)[-1]
                ginfo.seam_defs.add(name)
                ginfo.seams.add(name)
    # divergent-returner fixpoint (2 rounds covers one indirection hop).
    # Matching is by bare name, so require CONSENSUS: a name counts only
    # when EVERY definition of it in the scan set returns divergent —
    # one `def get()` returning time.monotonic() must not taint every
    # dict/env `.get()` call in the tree.  Collective/seam names are
    # excluded outright: a collective's result is coordinated by
    # construction (psum returns the same sum on every rank).
    for _ in range(2):
        reasons, disqualified = {}, set()
        for _rel, _src, tree in parsed:
            for qual, fn, decs in _iter_functions(tree):
                name = qual.rsplit(".", 1)[-1]
                if qual == "<module>" or name in ginfo.seams or \
                        (name.startswith("__") and name.endswith("__")):
                    continue
                fp = _FunctionPass(fn, qual, ginfo,
                                   is_seam=name in ginfo.seam_defs)
                fp.collect_taint()
                if fp.return_taint:
                    reasons.setdefault(name, fp.return_taint)
                else:
                    disqualified.add(name)
        ginfo.divergent_fns = {k: v for k, v in reasons.items()
                               if k not in disqualified}

    for rel, source, tree in parsed:
        suppress = _suppressions(source)
        fn_lines = {}       # def line -> suppression set, for whole-fn
        for qual, fn, decs in _iter_functions(tree):
            name = qual.rsplit(".", 1)[-1]
            fp = _FunctionPass(fn, qual, ginfo,
                               is_seam=name in ginfo.seam_defs)
            def_line = getattr(fn, "lineno", 0)
            fn_sup = suppress.get(def_line, set()) | \
                suppress.get(def_line - 1, set())
            for rule, line, message in fp.run():
                ids = (suppress.get(line, set())
                       | suppress.get(line - 1, set()) | fn_sup)
                if "all" in ids or rule in ids:
                    continue
                findings.append({
                    "rule": rule, "line": line,
                    "anchor": "%s:%s" % (rel, qual),
                    "message": "%s [in %s]" % (message, qual)})
        del fn_lines
    findings.sort(key=lambda f: (f["anchor"], f["line"], f["rule"]))
    return findings


# ----------------------------------------------------------------------
# rule registration
# ----------------------------------------------------------------------
def _source_findings(ctx):
    if "divergence" not in ctx.cache:
        ctx.cache["divergence"] = analyze_source_paths(ctx.source_paths)
    return ctx.cache["divergence"]


@register_rule("MXL-D004", "error",
               "rank-divergent value flows into a coordinated path")
def divergent_coordinated_path(ctx):
    """pid/clock/rank-tainted argument handed to a multi-host
    checkpoint protocol that needs the same value on every rank."""
    if not ctx.source_paths:
        return
    for f in _source_findings(ctx):
        if f["rule"] == "MXL-D004":
            ctx.report(None, f["message"],
                       severity=f.get("severity"),
                       anchor=f["anchor"], line=f["line"])


@register_rule("MXL-D005", "error",
               "collective gated on rank-divergent control flow")
def divergent_collective_condition(ctx):
    """A collective whose call condition, loop trip count, or
    reachability differs across ranks: a static deadlock."""
    if not ctx.source_paths:
        return
    for f in _source_findings(ctx):
        if f["rule"] == "MXL-D005":
            ctx.report(None, f["message"],
                       anchor=f["anchor"], line=f["line"])


@register_rule("MXL-D006", "warning",
               "unbalanced collective on an exception edge")
def unbalanced_collective_exception(ctx):
    """An exception path that can exit between paired collectives, or
    swallow a failing collective on one rank only."""
    if not ctx.source_paths:
        return
    for f in _source_findings(ctx):
        if f["rule"] == "MXL-D006":
            ctx.report(None, f["message"],
                       anchor=f["anchor"], line=f["line"])
