"""Bind-contract passes (rule family MXL-B).

Statically mirror ``Executor.__init__``'s argument/gradient handling and
reject the inputs it would mishandle *silently*:

- MXL-B001  ``grad_req="write"`` on a grad buffer shared by several
            arguments — each backward overwrites the previous argument's
            gradient; shared buffers need ``"add"`` — error;
- MXL-B002  args_grad provided for some-but-not-all non-null grad_req
            arguments — the executor silently downgrades the missing
            ones to ``"null"`` and they simply never train — warning;
- MXL-B003  auxiliary-state name collisions (aux_dict zip drops all but
            one) — error;
- MXL-B004  grad_req value outside null/write/add — error;
- MXL-B005  ``ctx_group`` attr referencing a group absent from a
            non-empty ``group2ctx`` map (the node lands on the default
            device without a word) — warning.

These run only when bind context is present on the AnalysisContext; a
pure ``Symbol.validate()`` with no bind arguments skips them.
"""
from __future__ import annotations

from .core import register_rule

_VALID_REQ = ("null", "write", "add")


def _req_map(ctx, arg_names):
    """Normalize grad_req exactly as Executor.__init__ does; None when no
    grad_req was supplied."""
    gr = ctx.grad_req
    if gr is None:
        return None
    if isinstance(gr, str):
        return {n: gr for n in arg_names}
    if isinstance(gr, (list, tuple)):
        return dict(zip(arg_names, gr))
    return {n: gr.get(n, "null") for n in arg_names}


def _grad_buffers(ctx, arg_names):
    """name -> grad buffer (or None), aligned like _as_list."""
    ag = ctx.args_grad
    if ag is None:
        return None
    if isinstance(ag, dict):
        return {n: ag.get(n) for n in arg_names}
    ag = list(ag)
    return dict(zip(arg_names, ag + [None] * (len(arg_names) - len(ag))))


def _storage_key(buf):
    """Identity key detecting aliased buffers: the NDArray object or its
    underlying storage when exposed."""
    data = getattr(buf, "_storage", None)
    return id(data) if data is not None else id(buf)


@register_rule("MXL-B001", "error",
               "grad_req=write on a shared grad buffer")
def aliased_grad_write(ctx):
    """Two write-req arguments writing one buffer: last writer wins."""
    arg_names = ctx.symbol.list_arguments()
    reqs = _req_map(ctx, arg_names)
    bufs = _grad_buffers(ctx, arg_names)
    if not bufs:
        return
    by_buf = {}
    for n in arg_names:
        buf = bufs.get(n)
        if buf is None:
            continue
        req = (reqs or {}).get(n, "write")
        if req == "write":
            by_buf.setdefault(_storage_key(buf), []).append(n)
    for names in by_buf.values():
        if len(names) > 1:
            for n in names:
                ctx.report(n, "grad_req='write' but args_grad[%r] is "
                           "shared with %s — each backward overwrites "
                           "the others' gradient; use grad_req='add' "
                           "for shared buffers"
                           % (n, [m for m in names if m != n]))


@register_rule("MXL-B002", "warning",
               "partially-provided args_grad silently downgraded")
def missing_grad_entries(ctx):
    """Some non-null-req args have grad buffers, others don't: the
    executor downgrades the missing ones to null and they never train."""
    arg_names = ctx.symbol.list_arguments()
    reqs = _req_map(ctx, arg_names)
    bufs = _grad_buffers(ctx, arg_names)
    if not bufs or not any(b is not None for b in bufs.values()):
        return      # forward-only bind: intentional
    for n in arg_names:
        req = (reqs or {}).get(n, "write")
        if req != "null" and bufs.get(n) is None:
            ctx.report(n, "grad_req=%r for %r but args_grad has no "
                       "buffer for it: bind silently downgrades it to "
                       "'null' and the parameter never updates" % (req, n))


@register_rule("MXL-B003", "error", "auxiliary state name collision")
def aux_collision(ctx):
    """Duplicate aux names: aux_dict keeps only the last one."""
    seen = {}
    for node in ctx.op_nodes():
        for aux in node.op.list_auxiliary_states():
            full = "%s_%s" % (node.name, aux)
            if full in seen:
                ctx.report(node, "auxiliary state %r collides with the "
                           "one from node %r: aux_dict keeps only one "
                           "buffer" % (full, seen[full]))
            else:
                seen[full] = node.name


@register_rule("MXL-B004", "error", "invalid grad_req value")
def bad_grad_req(ctx):
    """grad_req outside null/write/add (bind raises, but late)."""
    arg_names = ctx.symbol.list_arguments()
    reqs = _req_map(ctx, arg_names)
    if reqs is None:
        return
    for n in arg_names:
        req = reqs.get(n, "null")
        if req not in _VALID_REQ:
            ctx.report(n, "grad_req %r for %r is not one of %s"
                       % (req, n, list(_VALID_REQ)))


@register_rule("MXL-B005", "warning",
               "ctx_group not present in group2ctx")
def unmapped_ctx_group(ctx):
    """A node pinned to a device group the bind call doesn't map: it
    silently lands on the default device."""
    if not ctx.group2ctx:   # no grouping requested: attrs are inert
        return
    for node in ctx.topo:
        group = node.attrs.get("ctx_group")
        if group and group not in ctx.group2ctx:
            ctx.report(node, "ctx_group %r on node %r is not in "
                       "group2ctx %s: the node falls back to the "
                       "default device"
                       % (group, node.name, sorted(ctx.group2ctx)))
