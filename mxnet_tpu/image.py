"""Host-side image decode/encode/augmentation.

TPU-native counterpart of the reference's OpenCV-backed image path
(``src/io/image_augmenter.h``/``image_aug_default.cc``, ``imdecode`` NDArray
function ``src/ndarray/ndarray.cc:919-944``).  Decode runs on host CPU (the
reference's OMP decode threads, iter_image_recordio.cc:184-234); augmented
uint8/float arrays are shipped to device once per batch.  Uses OpenCV when
importable, else PIL — both are decode-only dependencies, never on the
compute path.
"""
from __future__ import annotations

import io as _io

import numpy as _np

__all__ = ["imdecode_bytes", "imencode", "augment", "imresize"]

try:
    import cv2 as _cv2
except Exception:  # pragma: no cover
    _cv2 = None
try:
    from PIL import Image as _PILImage
except Exception:  # pragma: no cover
    _PILImage = None


def imdecode_bytes(buf, iscolor=1):
    """Decode an encoded image buffer to an HWC uint8 RGB array."""
    buf = bytes(buf)
    if _cv2 is not None:
        flag = _cv2.IMREAD_COLOR if iscolor != 0 else _cv2.IMREAD_GRAYSCALE
        img = _cv2.imdecode(_np.frombuffer(buf, dtype=_np.uint8), flag)
        if img is None:
            raise ValueError("cannot decode image")
        if img.ndim == 2:
            img = img[:, :, None]
        else:
            img = _cv2.cvtColor(img, _cv2.COLOR_BGR2RGB)
        return img
    if _PILImage is not None:
        img = _PILImage.open(_io.BytesIO(buf))
        img = img.convert("L" if iscolor == 0 else "RGB")
        arr = _np.asarray(img, dtype=_np.uint8)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr
    raise ImportError("image decoding requires cv2 or PIL")


def imencode(img, quality=95, img_fmt=".jpg"):
    """Encode an HWC uint8 array to JPEG/PNG bytes."""
    img = _np.asarray(img, dtype=_np.uint8)
    if img.ndim == 2:
        img = img[:, :, None]
    if _cv2 is not None:
        enc = img if img.shape[2] == 1 else _cv2.cvtColor(img, _cv2.COLOR_RGB2BGR)
        params = [_cv2.IMWRITE_JPEG_QUALITY, quality] \
            if img_fmt.lower() in (".jpg", ".jpeg") else []
        ok, buf = _cv2.imencode(img_fmt, enc, params)
        if not ok:
            raise ValueError("cannot encode image")
        return buf.tobytes()
    if _PILImage is not None:
        mode = "L" if img.shape[2] == 1 else "RGB"
        pimg = _PILImage.fromarray(img.squeeze() if mode == "L" else img, mode)
        bio = _io.BytesIO()
        fmt = "JPEG" if img_fmt.lower() in (".jpg", ".jpeg") else "PNG"
        pimg.save(bio, format=fmt, quality=quality)
        return bio.getvalue()
    raise ImportError("image encoding requires cv2 or PIL")


def imresize(img, w, h):
    if _cv2 is not None:
        out = _cv2.resize(img, (w, h), interpolation=_cv2.INTER_LINEAR)
        if out.ndim == 2:
            out = out[:, :, None]
        return out
    pimg = _PILImage.fromarray(img.squeeze() if img.shape[2] == 1 else img)
    out = _np.asarray(pimg.resize((w, h), _PILImage.BILINEAR), dtype=img.dtype)
    if out.ndim == 2:
        out = out[:, :, None]
    return out


def _jitter_hsl(img, dh, ds, dl, rng):
    """Random hue/saturation/lightness shift (image_aug_default.cc HSL).

    Offsets are drawn uniform in [-d, d] per channel, matching the
    reference's random_h/random_s/random_l semantics on 0-255 images.
    """
    if dh <= 0 and ds <= 0 and dl <= 0:
        return img
    if img.shape[2] != 3 or _cv2 is None:
        # grayscale or no cv2: lightness jitter only
        off = rng.uniform(-dl, dl) if dl > 0 else 0.0
        return _np.clip(img.astype(_np.float32) + off, 0, 255).astype(img.dtype)
    hls = _cv2.cvtColor(img, _cv2.COLOR_RGB2HLS).astype(_np.float32)
    if dh > 0:
        hls[:, :, 0] = (hls[:, :, 0] + rng.uniform(-dh, dh) * 180.0 / 255.0) % 180.0
    if dl > 0:
        hls[:, :, 1] = hls[:, :, 1] + rng.uniform(-dl, dl)
    if ds > 0:
        hls[:, :, 2] = hls[:, :, 2] + rng.uniform(-ds, ds)
    hls = _np.clip(hls, 0, 255)
    hls[:, :, 0] = _np.clip(hls[:, :, 0], 0, 179)
    return _cv2.cvtColor(hls.astype(_np.uint8), _cv2.COLOR_HLS2RGB)


def _affine_warp(img, M, out_w, out_h, fill_value=255):
    """Inverse-map affine warp with bilinear sampling and constant fill —
    the numpy form of the reference's cv::warpAffine(M, BORDER_CONSTANT,
    fill_value) geometry path."""
    if _cv2 is not None:
        out = _cv2.warpAffine(
            img, M[:2], (out_w, out_h), flags=_cv2.INTER_LINEAR,
            borderMode=_cv2.BORDER_CONSTANT,
            borderValue=tuple([float(fill_value)] * 3))
        if out.ndim == 2:
            out = out[:, :, None]
        return out.astype(img.dtype)
    A = _np.vstack([M[:2], [0.0, 0.0, 1.0]])
    Ainv = _np.linalg.inv(A)
    ys, xs = _np.mgrid[0:out_h, 0:out_w]
    src_x = Ainv[0, 0] * xs + Ainv[0, 1] * ys + Ainv[0, 2]
    src_y = Ainv[1, 0] * xs + Ainv[1, 1] * ys + Ainv[1, 2]
    h, w = img.shape[:2]
    x0 = _np.floor(src_x).astype(_np.int64)
    y0 = _np.floor(src_y).astype(_np.int64)
    fx = (src_x - x0)[..., None]
    fy = (src_y - y0)[..., None]
    out = _np.full((out_h, out_w, img.shape[2]), float(fill_value))
    valid = (src_x >= 0) & (src_x <= w - 1) & (src_y >= 0) & (src_y <= h - 1)
    x0c = _np.clip(x0, 0, w - 2)
    y0c = _np.clip(y0, 0, h - 2)
    f = img.astype(_np.float64)
    samp = (f[y0c, x0c] * (1 - fx) * (1 - fy)
            + f[y0c, x0c + 1] * fx * (1 - fy)
            + f[y0c + 1, x0c] * (1 - fx) * fy
            + f[y0c + 1, x0c + 1] * fx * fy)
    out[valid] = samp[valid]
    return _np.clip(out, 0, 255).astype(img.dtype)


def augment(img, data_shape, rand_crop=False, rand_mirror=False, rng=None,
            max_rotate_angle=0, rotate=-1, min_random_scale=1.0,
            max_random_scale=1.0, max_aspect_ratio=0.0,
            max_shear_ratio=0.0, min_crop_size=-1, max_crop_size=-1,
            min_img_size=0.0, max_img_size=1e10, pad=0, fill_value=255,
            random_h=0, random_s=0, random_l=0):
    """Default augmenter (parity: image_aug_default.cc
    DefaultImageAugmenter): affine scale/aspect/shear/rotate with
    constant fill, pad, random-size or fixed crop to data_shape (C,H,W),
    mirror, HSL jitter.  All knobs default off, matching the reference's
    ImageRecordIter parameter defaults."""
    rng = rng or _np.random
    c, th, tw = data_shape
    if (min_crop_size > 0) != (max_crop_size > 0):
        raise ValueError("min_crop_size and max_crop_size must be set "
                         "together (reference CHECK)")
    if min_crop_size > 0 and min_crop_size > max_crop_size:
        raise ValueError("min_crop_size must be <= max_crop_size")
    use_affine = (max_rotate_angle > 0 or rotate > 0
                  or max_shear_ratio > 0.0 or max_aspect_ratio > 0.0
                  or min_img_size != 0.0 or max_img_size != 1e10)
    if use_affine:
        # the reference's combined matrix (image_aug_default.cc): shear s,
        # rotation (a, b), scale split across axes by the aspect ratio
        s = rng.uniform(0, 1) * max_shear_ratio * 2 - max_shear_ratio
        angle = int(rng.uniform(-max_rotate_angle, max_rotate_angle)) \
            if max_rotate_angle > 0 else 0
        if rotate > 0:
            angle = rotate
        a = _np.cos(angle / 180.0 * _np.pi)
        b = _np.sin(angle / 180.0 * _np.pi)
        scale = rng.uniform(min_random_scale, max_random_scale)
        ratio = rng.uniform(0, 1) * max_aspect_ratio * 2 \
            - max_aspect_ratio + 1
        hs = 2 * scale / (1 + ratio)
        ws = ratio * hs
        h, w = img.shape[:2]
        new_w = max(min_img_size, min(max_img_size, scale * w))
        new_h = max(min_img_size, min(max_img_size, scale * h))
        M = _np.zeros((2, 3))
        M[0, 0] = hs * a - s * b * ws
        M[1, 0] = -b * ws
        M[0, 1] = hs * b + s * a * ws
        M[1, 1] = a * ws
        ori_cw = M[0, 0] * w + M[0, 1] * h
        ori_ch = M[1, 0] * w + M[1, 1] * h
        M[0, 2] = (new_w - ori_cw) / 2
        M[1, 2] = (new_h - ori_ch) / 2
        img = _affine_warp(img, M, int(round(new_w)), int(round(new_h)),
                           fill_value)
    elif max_random_scale != 1.0 or min_random_scale != 1.0:
        s = rng.uniform(min_random_scale, max_random_scale)
        h, w = img.shape[:2]
        img = imresize(img, max(tw, int(w * s + 0.5)), max(th, int(h * s + 0.5)))
    if pad > 0:
        img = _np.pad(img, ((pad, pad), (pad, pad), (0, 0)),
                      constant_values=fill_value)
    if min_crop_size > 0 and max_crop_size > 0:
        # random square crop in [min, max] then resize to the target
        # (image_aug_default.cc random-crop-size branch)
        h, w = img.shape[:2]
        hi = min(max_crop_size, min(h, w))
        lo = min(min_crop_size, hi)
        size = int(rng.uniform(0, 1) * (hi - lo + 1)) + lo \
            if hi > lo else hi
        y, x = h - size, w - size
        if rand_crop:
            y = rng.randint(0, y + 1)
            x = rng.randint(0, x + 1)
        else:
            y //= 2
            x //= 2
        img = imresize(img[y:y + size, x:x + size], tw, th)
    h, w = img.shape[:2]
    # upscale if needed so a crop fits
    if h < th or w < tw:
        scale = max(th / h, tw / w)
        img = imresize(img, max(tw, int(w * scale + 0.5)),
                       max(th, int(h * scale + 0.5)))
        h, w = img.shape[:2]
    if rand_crop:
        y = rng.randint(0, h - th + 1)
        x = rng.randint(0, w - tw + 1)
    else:
        y = (h - th) // 2
        x = (w - tw) // 2
    img = img[y:y + th, x:x + tw]
    if rand_mirror and rng.randint(0, 2):
        img = img[:, ::-1]
    if random_h or random_s or random_l:
        img = _jitter_hsl(img, random_h, random_s, random_l, rng)
    if img.shape[2] != c:
        if c == 1:
            img = img.mean(axis=2, keepdims=True).astype(img.dtype)
        else:
            img = _np.repeat(img[:, :, :1], c, axis=2)
    return img
