"""Symbol: the symbolic graph API.

TPU-native reimplementation of the reference's Symbol/StaticGraph
(``src/symbol/symbol.cc``, ``include/mxnet/symbolic.h:40-317``).  The DAG is
plain Python nodes; *execution* happens by tracing the whole graph into one
jax function that XLA compiles (executor.py) — the reference's
Symbol→StaticGraph→GraphExecutor pipeline collapses into Symbol→trace→jit
(SURVEY §3.2: "This function is what becomes jax.jit tracing + XLA compile").

Kept reference semantics:
- composition with auto-created variables (``fc1_weight``) and NameManager
  auto-naming (symbol.cc:335,403),
- DFS-order ``list_arguments``/``list_outputs``/``list_auxiliary_states``,
- partial shape inference that *fills parameter shapes from data shapes*
  (static_graph.cc:59 InferNodeShapes) — what makes ``simple_bind`` work,
- attrs (``ctx_group``, ``lr_mult``, ``__shape__`` hints), AttrScope scoping,
- JSON save/load in the reference's nodes/arg_nodes/heads layout
  (static_graph.cc JSON ~:60-270) for checkpoint compatibility.
"""
from __future__ import annotations

import json

import numpy as _np

from .base import MXNetError
from .attribute import AttrScope
from .name import NameManager
from .ops.registry import (OP_REGISTRY, IncompleteShape, create_operator)

__all__ = ["Symbol", "Variable", "Group", "load", "load_json"]


class _Node:
    __slots__ = ("op", "name", "inputs", "attrs")

    def __init__(self, op, name, inputs, attrs):
        self.op = op            # OperatorProperty | None (=> variable)
        self.name = name
        self.inputs = inputs    # list[(node, out_index)]
        self.attrs = dict(attrs or {})

    @property
    def is_variable(self):
        return self.op is None

    @property
    def num_outputs(self):
        return 1 if self.op is None else self.op.num_outputs


def _topo_order(head_nodes):
    """Post-DFS order (parity: static_graph.cc:17 PostDFSOrder)."""
    order, visited = [], set()
    for head in head_nodes:
        stack = [(head, 0)]
        while stack:
            node, child_idx = stack.pop()
            if id(node) in visited and child_idx == 0:
                continue
            if child_idx < len(node.inputs):
                stack.append((node, child_idx + 1))
                child = node.inputs[child_idx][0]
                if id(child) not in visited:
                    stack.append((child, 0))
            else:
                if id(node) not in visited:
                    visited.add(id(node))
                    order.append(node)
    return order


class Symbol:
    """Handle to one or more output entries of the DAG."""

    def __init__(self, heads):
        self._heads = list(heads)  # list[(node, out_index)]

    # -- naming / attrs ----------------------------------------------------
    @property
    def name(self):
        if len(self._heads) == 1:
            return self._heads[0][0].name
        return None

    def attr(self, key):
        return self._heads[0][0].attrs.get(key)

    def _set_attr(self, **kwargs):
        for k, v in kwargs.items():
            self._heads[0][0].attrs[k] = str(v)

    def list_attr(self):
        return dict(self._heads[0][0].attrs)

    def attr_dict(self):
        out = {}
        for node in self._topo():
            if node.attrs:
                out[node.name] = dict(node.attrs)
        return out

    # -- traversal ---------------------------------------------------------
    def _topo(self):
        return _topo_order([n for n, _ in self._heads])

    def list_arguments(self):
        return [n.name for n in self._topo() if n.is_variable]

    def list_outputs(self):
        out = []
        for node, idx in self._heads:
            if node.is_variable:
                out.append(node.name)
            else:
                names = node.op.list_outputs()
                out.append("%s_%s" % (node.name, names[idx]))
        return out

    def list_auxiliary_states(self):
        out = []
        for node in self._topo():
            if not node.is_variable:
                for aux in node.op.list_auxiliary_states():
                    out.append("%s_%s" % (node.name, aux))
        return out

    def get_internals(self):
        heads = []
        for node in self._topo():
            for i in range(node.num_outputs):
                heads.append((node, i))
        return Symbol(heads)

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise MXNetError("cannot find output %r in %s" % (index, names))
            index = names.index(index)
        return Symbol([self._heads[index]])

    def __len__(self):
        return len(self._heads)

    def __iter__(self):
        return (self[i] for i in range(len(self._heads)))

    def __repr__(self):
        name = self.name
        return "<Symbol %s>" % (name if name else "Grouped")

    # -- composition sugar -------------------------------------------------
    def __call__(self, *args, **kwargs):
        raise MXNetError("Symbol composition via __call__ is not supported; "
                         "pass symbols as op arguments instead")

    def _binop(self, other, op_name, scalar_op, reverse=False):
        if isinstance(other, Symbol):
            lhs, rhs = (other, self) if reverse else (self, other)
            return _create(op_name, lhs, rhs)
        attrs = {"scalar": float(other)}
        return _create(scalar_op, self, **attrs)

    def __add__(self, other):
        return self._binop(other, "_Plus", "_PlusScalar")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binop(other, "_Minus", "_MinusScalar")

    def __rsub__(self, other):
        if isinstance(other, Symbol):
            return other.__sub__(self)
        return _create("_RMinusScalar", self, scalar=float(other))

    def __mul__(self, other):
        return self._binop(other, "_Mul", "_MulScalar")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binop(other, "_Div", "_DivScalar")

    def __rtruediv__(self, other):
        if isinstance(other, Symbol):
            return other.__truediv__(self)
        return _create("_RDivScalar", self, scalar=float(other))

    __div__ = __truediv__
    __rdiv__ = __rtruediv__

    def __pow__(self, other):
        return self._binop(other, "_Power", "_PowerScalar")

    def __neg__(self):
        return _create("_MulScalar", self, scalar=-1.0)

    # -- inference ---------------------------------------------------------
    def infer_shape(self, *args, **kwargs):
        """Returns (arg_shapes, out_shapes, aux_shapes); (None,)*3 if incomplete."""
        arg_shapes, out_shapes, aux_shapes, complete = \
            self._infer_shape_impl(args, kwargs)
        if not complete:
            return None, None, None
        return arg_shapes, out_shapes, aux_shapes

    def infer_shape_partial(self, *args, **kwargs):
        a, o, x, _ = self._infer_shape_impl(args, kwargs)
        return a, o, x

    def _infer_shape_impl(self, args, kwargs):
        arg_names = self.list_arguments()
        known = {}
        if args:
            if len(args) > len(arg_names):
                raise MXNetError("too many positional shapes")
            for name, shape in zip(arg_names, args):
                if shape is not None:
                    known[name] = tuple(shape)
        for name, shape in kwargs.items():
            if name not in arg_names:
                raise MXNetError("infer_shape: unknown argument %r; arguments "
                                 "are %s" % (name, arg_names))
            known[name] = tuple(shape)

        topo = self._topo()
        shapes = {}  # (id(node), idx) -> tuple
        for node in topo:
            if node.is_variable:
                if node.name in known:
                    shapes[(id(node), 0)] = known[node.name]
                elif "__shape__" in node.attrs:
                    from .dparam import parse_tuple
                    shapes[(id(node), 0)] = parse_tuple(node.attrs["__shape__"])

        while True:  # fixpoint: forward fill + param backfill until no progress
            progress = False
            for node in topo:
                if node.is_variable:
                    continue
                in_shapes = [shapes.get((id(c), ci)) for c, ci in node.inputs]
                try:
                    full_in, outs, _aux = node.op.infer_shape(in_shapes)
                except IncompleteShape:
                    continue
                for (c, ci), s in zip(node.inputs, full_in):
                    key = (id(c), ci)
                    if s is not None:
                        prev = shapes.get(key)
                        if prev is not None and tuple(prev) != tuple(s):
                            raise MXNetError(
                                "shape mismatch for input of %s: %s vs %s"
                                % (node.name, prev, s))
                        if prev is None:
                            shapes[key] = tuple(s)
                            progress = True
                for i, s in enumerate(outs):
                    key = (id(node), i)
                    if shapes.get(key) is None:
                        shapes[key] = tuple(s)
                        progress = True
            if not progress:
                break

        node_by_name = {n.name: n for n in topo if n.is_variable}
        arg_shapes = [shapes.get((id(node_by_name[n]), 0)) for n in arg_names]
        out_shapes = [shapes.get((id(n), i)) for n, i in self._heads]
        aux_shapes = []
        for node in topo:
            if not node.is_variable:
                in_shapes = [shapes.get((id(c), ci)) for c, ci in node.inputs]
                try:
                    _, _, aux = node.op.infer_shape(in_shapes)
                except IncompleteShape:
                    aux = [None] * len(node.op.list_auxiliary_states())
                aux_shapes.extend(aux)
        complete = (all(s is not None for s in arg_shapes)
                    and all(s is not None for s in out_shapes)
                    and all(s is not None for s in aux_shapes))
        return arg_shapes, out_shapes, aux_shapes, complete

    def infer_type(self, *args, **kwargs):
        """Forward type propagation consulting per-op infer_type (Cast etc)."""
        arg_names = self.list_arguments()
        known = {}
        for name, t in zip(arg_names, args):
            if t is not None:
                known[name] = _np.dtype(t)
        for name, t in kwargs.items():
            if name not in arg_names:
                raise MXNetError("infer_type: unknown argument %r; arguments "
                                 "are %s" % (name, arg_names))
            known[name] = _np.dtype(t)
        base = next(iter(known.values()), _np.dtype(_np.float32))

        topo = self._topo()
        types = {}
        for node in topo:
            if node.is_variable:
                types[(id(node), 0)] = known.get(node.name, base)
        aux_types = []
        for node in topo:
            if node.is_variable:
                continue
            in_types = [types.get((id(c), ci)) for c, ci in node.inputs]
            full_in, outs, aux = node.op.infer_type(in_types)
            for (c, ci), t in zip(node.inputs, full_in):
                if types.get((id(c), ci)) is None and t is not None:
                    types[(id(c), ci)] = _np.dtype(t)
            for i, t in enumerate(outs):
                types[(id(node), i)] = _np.dtype(t) if t is not None else base
            aux_types.extend(_np.dtype(t) if t is not None else base for t in aux)
        node_by_name = {n.name: n for n in topo if n.is_variable}
        arg_types = [types.get((id(node_by_name[n]), 0), base) for n in arg_names]
        out_types = [types.get((id(n), i), base) for n, i in self._heads]
        return arg_types, out_types, aux_types

    # -- static analysis (analysis/) ---------------------------------------
    def validate(self, shapes=None, type_dict=None, mesh=None,
                 sharding_rules=None, target="tpu", select=None, skip=None,
                 kvstore=None, hbm_bytes=None, grad_req=None,
                 data_names=None, label_names=None, compute_dtype=None,
                 device_kind=None, world_size=None, **shape_kwargs):
        """Run the static lint passes over this graph; returns
        ``list[analysis.GraphIssue]``, most severe first.

        The pre-trace counterpart of the reference GraphExecutor's
        bind-time shape/type inference (static_graph.cc:59): catch
        shape/dtype conflicts, dead inputs, and non-lowerable ops before
        they become opaque XLA trace errors.  ``shapes`` (or shape
        kwargs, ``infer_shape`` style) and ``type_dict`` seed
        propagation; ``mesh``/``sharding_rules`` enable the SPMD passes
        (sharding propagation MXL-P, peak-HBM MXL-M, collective audit
        MXL-C) with ``kvstore``/``hbm_bytes``/``grad_req`` refining their
        context; ``compute_dtype``/``device_kind`` steer the static
        roofline (MXL-R); ``world_size`` (or
        ``MXTPU_LINT_DISTRIBUTED=1`` + ``MXTPU_LINT_WORLD_SIZE``)
        enables the distributed trace diff (MXL-D001..003) over
        ``__rank_cond__``/``__collective__`` attrs; ``select``/``skip``
        filter rule ids (wildcards work).
        """
        from .analysis import analyze
        known = dict(shapes or {})
        known.update(shape_kwargs)
        return analyze(self, shapes=known, type_dict=type_dict, mesh=mesh,
                       sharding_rules=sharding_rules, target=target,
                       kvstore=kvstore, hbm_bytes=hbm_bytes,
                       grad_req=grad_req, data_names=data_names,
                       label_names=label_names,
                       compute_dtype=compute_dtype,
                       device_kind=device_kind, world_size=world_size,
                       select=select, skip=skip)

    # -- binding (implemented in executor.py) ------------------------------
    def bind(self, ctx, args, args_grad=None, grad_req="write", aux_states=None,
             group2ctx=None, shared_exec=None, validate=None):
        from .executor import Executor
        return Executor(self, ctx, args, args_grad, grad_req, aux_states,
                        group2ctx=group2ctx, shared_exec=shared_exec,
                        validate=validate)

    def simple_bind(self, ctx, grad_req="write", type_dict=None, group2ctx=None,
                    shared_exec=None, validate=None, **kwargs):
        from .executor import simple_bind
        return simple_bind(self, ctx, grad_req=grad_req, type_dict=type_dict,
                           group2ctx=group2ctx, shared_exec=shared_exec,
                           validate=validate, **kwargs)

    # -- grad (Symbol::Grad symbol.cc:569) ---------------------------------
    def grad(self, wrt):
        """Gradient symbol (``Symbol::Grad`` parity, reference
        symbol.cc:569).

        Returns a new symbol whose arguments are this symbol's arguments
        plus one head-gradient variable per output — named
        ``<headnode>_<index>_grad`` exactly as the reference's backward
        pass names them (static_graph.cc:448-452) — and whose outputs are
        the gradients w.r.t. ``wrt`` (in order).  Where the reference
        splices explicit Backward nodes into the graph, here the whole
        subgraph runs under ``jax.vjp`` inside one traceable op: one XLA
        computation, no per-node backward dispatch.
        """
        if isinstance(wrt, str):
            wrt = [wrt]
        wrt = list(wrt)
        args = self.list_arguments()
        missing = [w for w in wrt if w not in args]
        if missing:
            raise MXNetError("Symbol.grad: %s not in arguments %s"
                             % (missing, args))
        op = _GradProp(self, wrt)
        name = NameManager.current().get(None, op.hint)
        attrs = dict(AttrScope.current().get(None))
        entries = [Variable(a)._heads[0] for a in op.list_arguments()]
        node = _Node(op, name, entries, attrs)
        return Symbol([(node, i) for i in range(op.num_outputs)])

    # -- pickling (reference Symbol __getstate__/__setstate__: the JSON
    # form IS the pickled state) -------------------------------------------
    def __getstate__(self):
        return {"handle": self.tojson()}

    def __setstate__(self, state):
        restored = load_json(state["handle"])
        self._heads = restored._heads

    # -- serialization (reference JSON layout) -----------------------------
    def tojson(self):
        topo = self._topo()
        node_index = {id(n): i for i, n in enumerate(topo)}
        nodes = []
        for n in topo:
            nodes.append({
                "op": "null" if n.is_variable else n.op.op_name,
                "name": n.name,
                "attr": {k: str(v) for k, v in n.attrs.items()},
                "inputs": [[node_index[id(c)], ci] for c, ci in n.inputs],
            })
        arg_nodes = [i for i, n in enumerate(topo) if n.is_variable]
        heads = [[node_index[id(n)], i] for n, i in self._heads]
        return json.dumps({"nodes": nodes, "arg_nodes": arg_nodes,
                           "heads": heads}, indent=2)

    def save(self, fname):
        from .stream import open_uri
        with open_uri(fname, "w") as fo:
            fo.write(self.tojson())

    def debug_str(self):
        lines = []
        for n in self._topo():
            if n.is_variable:
                lines.append("Variable:%s" % n.name)
            else:
                ins = ", ".join("%s[%d]" % (c.name, ci) for c, ci in n.inputs)
                lines.append("%s(%s) -> %s" % (n.op.op_name, ins, n.name))
        return "\n".join(lines)


class _GradProp:
    """Operator backing ``Symbol.grad`` (reference Symbol::Grad,
    symbol.cc:569 + MakeBackwardPass static_graph.cc:395).

    Holds the base symbol; ``forward`` evaluates the base graph's trace
    under ``jax.vjp`` and returns the cotangents of the requested
    arguments.  Arguments = base args + head-gradient inputs (reference
    naming ``<headnode>_<index>_grad``).  Not registered in OP_REGISTRY —
    a grad symbol is constructed, bound, and executed, not re-parsed from
    JSON (the reference's Grad symbols carry un-serializable
    backward_source_node pointers too).
    """

    param_cls = None
    op_name = "_Grad"
    hint = "grad"

    def __init__(self, base, wrt):
        from .executor import _build_program
        self.attrs = {}
        self.param = None
        self._base = base
        self._wrt = list(wrt)
        self._base_args = base.list_arguments()
        self._aux_names = base.list_auxiliary_states()
        self._head_names = ["%s_%d_grad" % (node.name, index)
                            for node, index in base._heads]
        prog = _build_program(base, {})
        self._trace = prog.trace
        self.need_rng = prog.needs_rng

    # -- metadata ---------------------------------------------------------
    def list_arguments(self):
        return list(self._base_args) + list(self._head_names)

    def list_outputs(self):
        return ["%s_grad" % w for w in self._wrt]

    def list_auxiliary_states(self):
        return list(self._aux_names)

    @property
    def num_outputs(self):
        return len(self._wrt)

    # -- inference --------------------------------------------------------
    def infer_shape(self, in_shapes):
        n = len(self._base_args)
        known = {k: v for k, v in zip(self._base_args, in_shapes[:n])
                 if v is not None}
        barg, bout, baux = self._base.infer_shape(**known)
        full_in = list(barg) + list(bout)   # head grads shaped like outputs
        out_shapes = [barg[self._base_args.index(w)] for w in self._wrt]
        return full_in, out_shapes, list(baux)

    def infer_type(self, in_types):
        # delegate to the base graph (mixed-dtype graphs: Embedding int
        # ids, Cast heads) the same way infer_shape does
        n = len(self._base_args)
        known = {k: t for k, t in zip(self._base_args, in_types[:n])
                 if t is not None}
        barg, bout, baux = self._base.infer_type(**known)
        full_in = list(barg) + list(bout)   # head grads typed like outputs
        out_types = [barg[self._base_args.index(w)] for w in self._wrt]
        return full_in, out_types, list(baux)

    # -- compute ----------------------------------------------------------
    def forward(self, inputs, aux, is_train, rng):
        import jax
        import jax.numpy as jnp
        from .executor import _zero_key
        n = len(self._base_args)
        arg_vals = dict(zip(self._base_args, inputs[:n]))
        head_grads = list(inputs[n:])
        aux_vals = dict(zip(self._aux_names, aux))
        key = rng if rng is not None else _zero_key()

        # the reference's backward pass differentiates the TRAINING
        # computation (BatchNorm batch stats, Dropout active) regardless
        # of the grad executor's own is_train flag
        def f(wrt_vals):
            merged = dict(arg_vals)
            merged.update(wrt_vals)
            return self._trace(merged, aux_vals, key, True)

        wrt_in = {w: arg_vals[w] for w in self._wrt}
        (outs, aux_out), vjp_fn = jax.vjp(f, wrt_in)
        cot = ([jnp.asarray(h, o.dtype) for h, o in zip(head_grads, outs)],
               jax.tree_util.tree_map(jnp.zeros_like, aux_out))
        grads = vjp_fn(cot)[0]
        return [grads[w] for w in self._wrt], None


def Variable(name, attr=None, shape=None, **kwargs):
    """Create a symbolic variable (parity symbol.cc CreateVariable)."""
    if not isinstance(name, str):
        raise TypeError("Variable name must be a string")
    attr = AttrScope.current().get(attr)
    if shape is not None:
        attr = dict(attr)
        attr["__shape__"] = str(tuple(shape))
    for k, v in kwargs.items():
        attr = dict(attr)
        attr[k] = str(v)
    return Symbol([(_Node(None, name, [], attr), 0)])


def _sym_or_scalar_binop(lhs, rhs, op_name, scalar_op, rscalar_op, what):
    """Module-level two-operand helper (reference symbol.py maximum/
    minimum/pow): symbol∘symbol, symbol∘scalar, or scalar∘symbol."""
    if isinstance(lhs, Symbol) and isinstance(rhs, Symbol):
        return _create(op_name, lhs, rhs)
    if isinstance(lhs, Symbol):
        return _create(scalar_op, lhs, scalar=float(rhs))
    if isinstance(rhs, Symbol):
        return _create(rscalar_op, rhs, scalar=float(lhs))
    raise MXNetError("%s needs at least one Symbol operand" % what)


def maximum(lhs, rhs):
    """Elementwise max (reference symbol.py maximum)."""
    return _sym_or_scalar_binop(lhs, rhs, "_Maximum", "_MaximumScalar",
                                "_MaximumScalar", "maximum")


def minimum(lhs, rhs):
    """Elementwise min (reference symbol.py minimum)."""
    return _sym_or_scalar_binop(lhs, rhs, "_Minimum", "_MinimumScalar",
                                "_MinimumScalar", "minimum")


def pow(lhs, rhs):  # noqa: A001 (reference name)
    """Elementwise power (reference symbol.py pow)."""
    return _sym_or_scalar_binop(lhs, rhs, "_Power", "_PowerScalar",
                                "_RPowerScalar", "pow")


def Group(symbols):
    heads = []
    for s in symbols:
        heads.extend(s._heads)
    return Symbol(heads)


def load_json(json_str):
    data = json.loads(json_str)
    nodes = []
    for spec in data["nodes"]:
        attrs = spec.get("attr", spec.get("param", {})) or {}
        inputs = [(nodes[i], ci) for i, ci, *_ in spec["inputs"]]
        if spec["op"] in ("null", "None"):
            node = _Node(None, spec["name"], [], attrs)
        else:
            cls = OP_REGISTRY.get(spec["op"])
            fields = cls.param_cls._fields if cls.param_cls is not None else {}
            # nodes may carry arbitrary user/graph attrs (ctx_group, lr_mult,
            # custom tags); only declared param fields configure the op —
            # except ops that take free-form kwargs (Custom, _Native)
            if getattr(cls, "accepts_any_attrs", False):
                op_kwargs = dict(attrs)
            else:
                op_kwargs = {k: v for k, v in attrs.items() if k in fields}
            op = create_operator(spec["op"], **op_kwargs)
            node = _Node(op, spec["name"], inputs, attrs)
        nodes.append(node)
    heads = [(nodes[i], ci) for i, ci, *_ in data["heads"]]
    return Symbol(heads)


def load(fname):
    from .stream import open_uri
    with open_uri(fname, "r") as fi:
        return load_json(fi.read())


# ----------------------------------------------------------------------
# op creator functions (parity: symbol.py:1090-1104 _init_symbol_module)
# ----------------------------------------------------------------------
def _create(op_name, *args, **kwargs):
    explicit_name = kwargs.pop("name", None)
    attr = kwargs.pop("attr", None)

    sym_kwargs = {}
    attr_kwargs = {}
    for k, v in kwargs.items():
        if isinstance(v, Symbol):
            sym_kwargs[k] = v
        else:
            attr_kwargs[k] = v

    pos_syms = []
    for a in args:
        if isinstance(a, Symbol):
            pos_syms.append(a)
        else:
            raise MXNetError("%s: positional arguments must be Symbols, got %r"
                             % (op_name, type(a)))

    cls = OP_REGISTRY.get(op_name)
    if getattr(cls, "param_cls", None) is not None and \
            "num_args" in cls.param_cls._fields and "num_args" not in attr_kwargs:
        attr_kwargs["num_args"] = len(pos_syms) + len(sym_kwargs)

    op = create_operator(op_name, **attr_kwargs)
    hint = op.hint or op_name.lower().strip("_")
    name = NameManager.current().get(explicit_name, hint)
    attrs = AttrScope.current().get(attr)
    attrs = dict(attrs)
    attrs.update(op.attrs)

    arg_names = op.list_arguments()
    inputs = {}
    for aname, s in zip(arg_names, pos_syms):
        inputs[aname] = s
    for aname, s in sym_kwargs.items():
        if aname not in arg_names:
            raise MXNetError("%s: unknown input %r; inputs are %s"
                             % (op_name, aname, arg_names))
        if aname in inputs:
            raise MXNetError("%s: input %r given twice" % (op_name, aname))
        inputs[aname] = s
    # auto-create missing inputs as variables named {name}_{arg}
    entries = []
    for aname in arg_names:
        if aname in inputs:
            s = inputs[aname]
            if len(s._heads) != 1:
                raise MXNetError("%s: input %r must have a single output"
                                 % (op_name, aname))
            entries.append(s._heads[0])
        else:
            var = Variable("%s_%s" % (name, aname))
            entries.append(var._heads[0])

    node = _Node(op, name, entries, attrs)
    return Symbol([(node, i) for i in range(op.num_outputs)])


def _make_creator(op_name):
    def creator(*args, **kwargs):
        return _create(op_name, *args, **kwargs)
    creator.__name__ = op_name
    cls = OP_REGISTRY.get(op_name)
    doc = cls.__doc__ or ""
    if getattr(cls, "param_cls", None) is not None:
        doc += "\n\nParameters\n----------\n" + cls.param_cls.describe()
    creator.__doc__ = doc
    return creator


def _init_symbol_module():
    """Inject one creator per registered op into this module's namespace."""
    g = globals()
    for name, _cls in OP_REGISTRY.items():
        if name not in g:
            g[name] = _make_creator(name)


from . import ops as _ops  # noqa: E402  (triggers op registration)
_init_symbol_module()
