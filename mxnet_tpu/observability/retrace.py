"""Runtime retrace sentry (``MXTPU_RETRACE_SENTRY=1``).

The live witness for the static MXL-X retrace-stability lint
(``analysis/retrace.py``): a test-mode monkeypatch of
``parallel.overlap.note_lowering`` and the program-registry miss path
(``executor._lookup_program``) that counts — and, crucially,
*attributes* — every lowering that happens after a serving warmup
boundary.  The zero-steady-state-lowerings contract says that number
is zero; when it is not, a bare counter only proves *that* something
retraced, while the sentry names *why*: it remembers the cache-key
components of every registry lookup (graph fingerprint, bind context
key, compute dtype) and, on an unexpected lowering, diffs the incoming
components against the closest previously-seen key and reports the
divergent ingredient in a structured ``retrace`` telemetry event (and
therefore the flight recorder, since every emit passes through it).

A bucket bypass shows up as ``graph_fingerprint`` divergence (a novel
prompt length built a novel prefill symbol); an env flip mid-serve
shows up as ``compute_dtype``; a lowering that never went through the
registry at all (a hot-path ``jax.jit`` — MXL-X003's runtime shape) is
attributed ``outside_program_registry`` with the calling site.

Lifecycle — mirrors how serving actually warms up:

- :func:`warmup_begin` disarms the sentry: a legitimate compile phase
  (model add, generation warmup, hot-swap of a new graph) is starting.
- :func:`warmup_boundary` arms it: steady state begins, every lowering
  from here on is a contract violation.

The sentry never raises — drills fail on the counters they stamp
(``retraces_after_warmup`` in the BENCH lines, ``stats()`` in tests),
so a sentry bug cannot take down a serving process.

Enable with ``MXTPU_RETRACE_SENTRY=1`` (CI does, for the serving and
resilience suites); :func:`maybe_install` is the env-gated entry.
"""
from __future__ import annotations

import os
import sys
import threading

__all__ = ["install", "uninstall", "installed", "maybe_install",
           "warmup_begin", "warmup_boundary", "armed", "stats",
           "attributions", "reset"]

_LOCK = threading.Lock()
_INSTALLED = False
_ARMED = False
_ORIG_NOTE_LOWERING = None
_ORIG_LOOKUP_PROGRAM = None

#: component dicts of recently seen registry keys (bounded)
_SEEN = []
_SEEN_MAX = 64

#: attribution records for post-warmup lowerings (bounded)
_ATTRIBUTIONS = []
_ATTRIBUTIONS_MAX = 32

_COUNTS = {"retraces_after_warmup": 0, "lowerings_seen": 0}

_TLS = threading.local()     # .incoming: component dict of the lookup
                             # currently in flight on this thread


def _caller_site():
    """file:line of the nearest frame outside this module and the
    overlap cache internals — where the lowering was requested."""
    frame = sys._getframe(2)
    skip = (__name__, "mxnet_tpu.parallel.overlap")
    while frame is not None and \
            frame.f_globals.get("__name__") in skip:
        frame = frame.f_back
    if frame is None:
        return "<unknown>"
    return "%s:%d" % (frame.f_code.co_filename, frame.f_lineno)


def _components(symbol, ctx_key):
    """The cache-key ingredients of one registry lookup, stringly —
    exactly what a divergence must be blamed on."""
    from ..parallel import overlap as _overlap
    try:
        gf = _overlap.graph_fingerprint(symbol)[:16]
    except Exception:
        gf = "<unfingerprintable>"
    return {
        "graph_fingerprint": gf,
        "ctx_key": repr(ctx_key),
        "compute_dtype": os.environ.get("MXNET_COMPUTE_DTYPE", ""),
    }


def _attribute(incoming):
    """Name the divergent cache-key ingredient(s): diff ``incoming``
    against the closest previously seen key (most matching
    components).  None incoming means the lowering never went through
    the program registry."""
    if incoming is None:
        return {"divergent": ["outside_program_registry"],
                "detail": {}}
    with _LOCK:
        seen = list(_SEEN)
    best, best_score = None, -1
    for prior in seen:
        if prior is incoming:
            continue
        score = sum(1 for k in incoming if prior.get(k) == incoming[k])
        if score > best_score:
            best, best_score = prior, score
    if best is None:
        return {"divergent": ["no_prior_key"], "detail": dict(incoming)}
    divergent = sorted(k for k in incoming
                       if best.get(k) != incoming[k])
    detail = {k: {"incoming": incoming[k], "closest_seen": best.get(k)}
              for k in divergent}
    return {"divergent": divergent or ["identical_key_relowered"],
            "detail": detail}


def _note_lowering_sentry(n=1):
    """Replacement for ``overlap.note_lowering``: count, and when
    armed, attribute + emit.  Never raises."""
    _ORIG_NOTE_LOWERING(n)
    try:
        incoming = getattr(_TLS, "incoming", None)
        site = _caller_site()
        with _LOCK:
            _COUNTS["lowerings_seen"] += n
            if not _ARMED:
                return
            _COUNTS["retraces_after_warmup"] += n
        attribution = _attribute(incoming)
        record = {"site": site,
                  "divergent": attribution["divergent"],
                  "detail": attribution["detail"]}
        with _LOCK:
            if len(_ATTRIBUTIONS) < _ATTRIBUTIONS_MAX:
                _ATTRIBUTIONS.append(record)
        from . import events as _events
        _events.emit("retrace", divergent=attribution["divergent"],
                     site=site, detail=attribution["detail"], n=n)
    except Exception:       # the sentry must never take serving down
        pass


def _lookup_program_sentry(symbol, ctx_key, group2ctx):
    """Replacement for ``executor._lookup_program``: remember the
    incoming key components so a lowering fired underneath can be
    diffed against every key seen before it."""
    try:
        incoming = _components(symbol, ctx_key)
    except Exception:
        incoming = None
    _TLS.incoming = incoming
    try:
        return _ORIG_LOOKUP_PROGRAM(symbol, ctx_key, group2ctx)
    finally:
        _TLS.incoming = None
        if incoming is not None:
            with _LOCK:
                if not any(p == incoming for p in _SEEN):
                    _SEEN.append(incoming)
                    if len(_SEEN) > _SEEN_MAX:
                        del _SEEN[0]


def install():
    """Patch the lowering counter and the registry miss path.
    Idempotent."""
    global _INSTALLED, _ORIG_NOTE_LOWERING, _ORIG_LOOKUP_PROGRAM
    if _INSTALLED:
        return
    from ..parallel import overlap as _overlap
    from .. import executor as _executor
    _ORIG_NOTE_LOWERING = _overlap.note_lowering
    _ORIG_LOOKUP_PROGRAM = _executor._lookup_program
    _overlap.note_lowering = _note_lowering_sentry
    _executor._lookup_program = _lookup_program_sentry
    _INSTALLED = True


def uninstall():
    """Restore the originals and disarm."""
    global _INSTALLED, _ARMED
    if not _INSTALLED:
        return
    from ..parallel import overlap as _overlap
    from .. import executor as _executor
    _overlap.note_lowering = _ORIG_NOTE_LOWERING
    _executor._lookup_program = _ORIG_LOOKUP_PROGRAM
    _INSTALLED = False
    _ARMED = False


def installed():
    return _INSTALLED


def maybe_install(env=os.environ):
    """Install iff ``MXTPU_RETRACE_SENTRY=1`` (the CI hook)."""
    if str(env.get("MXTPU_RETRACE_SENTRY", "")).strip().lower() in \
            ("1", "true", "yes", "on"):
        install()
        return True
    return False


def warmup_begin():
    """A legitimate compile phase is starting (model add, generation
    warmup, hot-swap): disarm so its lowerings are not counted as
    retraces.  Safe no-op when the sentry is not installed."""
    global _ARMED
    with _LOCK:
        _ARMED = False


def warmup_boundary():
    """Steady state begins: arm the sentry — every lowering from here
    on is counted and attributed.  Safe no-op when not installed."""
    global _ARMED
    if not _INSTALLED:
        return
    with _LOCK:
        _ARMED = True


def armed():
    return _ARMED


def stats():
    """{"installed", "armed", "retraces_after_warmup",
    "lowerings_seen", "attributions"} — the numbers the BENCH lines
    stamp and the drills assert on."""
    with _LOCK:
        return {"installed": _INSTALLED, "armed": _ARMED,
                "retraces_after_warmup":
                    _COUNTS["retraces_after_warmup"],
                "lowerings_seen": _COUNTS["lowerings_seen"],
                "attributions": [dict(a) for a in _ATTRIBUTIONS]}


def attributions():
    """The bounded attribution records (most recent run)."""
    with _LOCK:
        return [dict(a) for a in _ATTRIBUTIONS]


def reset():
    """Forget counters, seen keys and attributions; disarm (tests)."""
    global _ARMED
    with _LOCK:
        _ARMED = False
        _SEEN[:] = []
        _ATTRIBUTIONS[:] = []
        for k in _COUNTS:
            _COUNTS[k] = 0
