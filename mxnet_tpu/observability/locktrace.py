"""Runtime lock-discipline sanitizer (``MXTPU_LOCKCHECK=1``).

The live witness for the static MXL-Q002 lock-order lint
(``analysis/concurrency.py``): a test-mode monkeypatch of
``threading.Lock`` / ``threading.RLock`` that records, per thread, the
stack of locks currently held and where each was acquired.  Whenever a
thread acquires lock B while holding lock A, the (A before B) edge is
added to a process-global order graph; if the graph already contains
(B before A) — observed on any thread, at any earlier point in the
run — the acquisition raises a structured
``ResilienceError(kind="lock_order")`` naming both acquisition sites,
instead of letting the suites deadlock-or-pass by scheduling luck.

This catches *potential* deadlocks: the two opposing acquisitions never
have to interleave in the failing run, they only both have to happen.
That is exactly what a CI suite can provide — serving and resilience
tests exercise each code path once, the graph remembers.

Scope and honesty:

- Only locks **created after** :func:`install` are traced (the factory
  is patched, not existing objects).  ``tests/conftest.py`` installs
  before the package spins up any runtime state, so in practice every
  package lock is traced.
- ``threading.Condition`` cooperates for free: it delegates
  acquire/release to the wrapped lock, and ``wait()`` releases through
  the same traced methods, so a held-then-waited condition does not
  pin its edge.
- Re-acquiring an already-held traced RLock adds no edge (reentrancy
  is not an order).
- The order graph keys locks by **creation site** (``file:line``), not
  object identity: a thousand per-request locks born on one line are
  one node, which is also the right granularity for reporting.

Enable with ``MXTPU_LOCKCHECK=1`` (CI does, for the serving and
resilience suites); :func:`maybe_install` is the env-gated entry.
"""
from __future__ import annotations

import os
import sys
import threading
import traceback
import _thread

__all__ = ["install", "uninstall", "installed", "maybe_install",
           "order_edges", "reset_order_graph", "TracedLock",
           "TracedRLock"]

_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock

# native (untraced) leaf lock guarding the order graph itself
_GRAPH_LOCK = _thread.allocate_lock()
_EDGES = {}          # (site_a, site_b) -> (stack_a, stack_b) summaries
_INSTALLED = False

_TLS = threading.local()     # .held: list of (site, summary)


def _caller_site(depth=2):
    """file:line of the frame that called into the traced lock."""
    frame = sys._getframe(depth)
    # skip our own module frames (e.g. Condition delegating through us)
    while frame is not None and frame.f_globals.get("__name__") == \
            __name__:
        frame = frame.f_back
    if frame is None:
        return "<unknown>", "<unknown>"
    here = "%s:%d" % (frame.f_code.co_filename, frame.f_lineno)
    summary = "".join(traceback.format_stack(frame, limit=4))
    return here, summary


def _held_stack():
    held = getattr(_TLS, "held", None)
    if held is None:
        held = []
        _TLS.held = held
    return held


def _lock_order_error(site_a, site_b, stack_b, prior):
    from ..resilience import ResilienceError
    prior_a, prior_b = prior
    msg = (
        "lock-order inversion: this thread holds the lock from %s and "
        "is acquiring the lock from %s, but the opposite order was "
        "already observed in this process (MXL-Q002's runtime "
        "witness).\n--- this acquisition (%s while holding %s):\n%s"
        "--- prior opposite-order acquisition (%s while holding %s):\n%s"
        % (site_a, site_b, site_b, site_a, stack_b,
           site_a, site_b, prior_b))
    return ResilienceError(msg, phase="lockcheck", kind="lock_order")


class _TracedBase(object):
    """Shared acquire/release bookkeeping for traced Lock/RLock."""

    def __init__(self):
        site, _ = _caller_site(depth=3)
        self._mxtpu_site = site

    # -- the discipline check -----------------------------------------
    def _note_acquired(self, blocking=True):
        held = _held_stack()
        me = self._mxtpu_site
        if any(site is me or site == me for site, _s in held):
            # reentrant / same-site nesting: not an order
            held.append((me, ""))
            return
        _, summary = _caller_site(depth=3)
        err = None
        with _GRAPH_LOCK:
            for site_a, stack_a in held:
                if site_a == me:
                    continue
                key = (site_a, me)
                rev = (me, site_a)
                if rev in _EDGES and key not in _EDGES:
                    err = _lock_order_error(site_a, me, summary,
                                            _EDGES[rev])
                    break
                _EDGES.setdefault(key, (stack_a, summary))
        if err is not None:
            self._unlock_raw()
            raise err
        held.append((me, summary))

    def _note_released(self):
        held = _held_stack()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == self._mxtpu_site:
                del held[i]
                return

    def _at_fork_reinit(self):
        # stdlib (concurrent.futures, logging) reinits its module locks
        # in the forked child through this hook
        self._lock._at_fork_reinit()


class TracedLock(_TracedBase):
    """Drop-in for ``threading.Lock()`` with order tracing."""

    def __init__(self):
        _TracedBase.__init__(self)
        self._lock = _ORIG_LOCK()

    def acquire(self, blocking=True, timeout=-1):
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._note_acquired()
        return got

    def release(self):
        self._note_released()
        self._lock.release()

    def _unlock_raw(self):
        self._lock.release()

    def locked(self):
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return "<TracedLock %s %r>" % (
            "locked" if self._lock.locked() else "unlocked",
            self._mxtpu_site)


class TracedRLock(_TracedBase):
    """Drop-in for ``threading.RLock()`` with order tracing.  Keeps the
    underscore protocol (``_is_owned`` etc.) so ``threading.Condition``
    waits release/reacquire through the traced path."""

    def __init__(self):
        _TracedBase.__init__(self)
        self._lock = _ORIG_RLOCK()

    def acquire(self, blocking=True, timeout=-1):
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._note_acquired()
        return got

    def release(self):
        self._note_released()
        self._lock.release()

    def _unlock_raw(self):
        self._lock.release()

    # Condition integration: delegate the underscore protocol but keep
    # our bookkeeping consistent across wait()'s release/reacquire.
    def _is_owned(self):
        return self._lock._is_owned()

    def _release_save(self):
        self._note_released()
        return self._lock._release_save()

    def _acquire_restore(self, state):
        self._lock._acquire_restore(state)
        self._note_acquired()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return "<TracedRLock %r>" % (self._mxtpu_site,)


def install():
    """Patch ``threading.Lock``/``RLock`` factories with the traced
    versions.  Idempotent."""
    global _INSTALLED
    if _INSTALLED:
        return
    threading.Lock = TracedLock
    threading.RLock = TracedRLock
    _INSTALLED = True


def uninstall():
    """Restore the native factories (existing traced locks keep
    working — they wrap real locks)."""
    global _INSTALLED
    threading.Lock = _ORIG_LOCK
    threading.RLock = _ORIG_RLOCK
    _INSTALLED = False


def installed():
    return _INSTALLED


def maybe_install(env=os.environ):
    """Install iff ``MXTPU_LOCKCHECK=1`` (the CI hook)."""
    if str(env.get("MXTPU_LOCKCHECK", "")).strip().lower() in \
            ("1", "true", "yes", "on"):
        install()
        return True
    return False


def order_edges():
    """Snapshot of the observed (A before B) site pairs."""
    with _GRAPH_LOCK:
        return sorted(_EDGES)


def reset_order_graph():
    """Forget observed edges + this thread's held stack (tests)."""
    with _GRAPH_LOCK:
        _EDGES.clear()
    _TLS.held = []
