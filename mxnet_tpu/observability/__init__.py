"""Pod-wide telemetry: structured event log, phase spans, counters,
cross-rank aggregation.

Off by default.  Set ``MXTPU_TELEMETRY=1`` (and optionally
``MXTPU_TELEMETRY_DIR=/some/scratch``) and every rank appends typed
JSONL records — step timings, phase spans, derived counters, faults,
checkpoint lifecycle, collective traffic — to its own
``events-rank*.jsonl``.  ``tools/mxtop.py`` renders the merged pod
report; :mod:`.aggregate` publishes live per-rank summaries over the
coordination-service KV.  Schema and usage: docs/observability.md.

The fit loops / trainer / kvstore / resilience seams call
:func:`record_step` and :func:`spans.span`; both are cheap no-ops when
telemetry is off, so the default path pays one cached boolean check.
"""
from __future__ import annotations

from . import (events, spans, counters, aggregate, phases, trace,
               flight, slo, locktrace, retrace, metrics, sloengine)
from .events import (enabled, emit, flush, refresh, run_id, last_fault,
                     EventLog)
from .phases import PHASES, TRAIN_PHASES, SERVE_PHASES
from .spans import span, timed_iter, SPAN_NAMES, overlap_report
from .counters import (StepStats, percentile, global_stats,
                       emit_trainer_counters, emit_sentinel_counters)
from .aggregate import (publish_summary, collect_summaries,
                        heartbeat_ages, pod_view, read_events,
                        build_report, EventTailer)

__all__ = [
    "events", "spans", "counters", "aggregate", "phases", "trace",
    "flight", "slo", "locktrace", "retrace", "metrics", "sloengine",
    "enabled", "emit", "flush", "refresh", "run_id", "last_fault",
    "EventLog",
    "PHASES", "TRAIN_PHASES", "SERVE_PHASES",
    "span", "timed_iter", "SPAN_NAMES", "overlap_report",
    "StepStats", "percentile", "global_stats",
    "emit_trainer_counters", "emit_sentinel_counters",
    "publish_summary", "collect_summaries", "heartbeat_ages",
    "pod_view", "read_events", "build_report", "EventTailer",
    "record_step",
]

#: publish a KV summary every N recorded steps (override via env)
_PUBLISH_EVERY = 10


def record_step(step, dur_s, batch_size=None, epoch=None, **fields):
    """The one call a training loop makes per step when telemetry is
    on: emits the ``step`` record, folds the timing into the process
    :class:`StepStats`, and every ``_PUBLISH_EVERY`` steps pushes the
    compact summary to the coordination KV for the live pod view.
    No-op when telemetry is off (the step still lands in the crash
    flight recorder's bounded ring); never raises."""
    try:
        flight.note("step", step, {"dur_ms": round(float(dur_s) * 1e3, 3)})
    except Exception:
        pass
    log = events.get()
    if log is None:
        return
    try:
        stats = counters.global_stats()
        stats.observe(dur_s, step=step, batch_size=batch_size)
        rec = {"dur_ms": round(float(dur_s) * 1e3, 3)}
        if batch_size:
            rec["batch_size"] = batch_size
            if dur_s > 0:
                rec["samples_per_sec"] = round(batch_size / dur_s, 2)
        if epoch is not None:
            rec["epoch"] = epoch
        rec.update(fields)
        log.emit("step", step=step, **rec)
        if step is not None and step % _PUBLISH_EVERY == 0:
            aggregate.publish_summary(step=step)
    except Exception:
        pass
