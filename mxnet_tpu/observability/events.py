"""Structured per-rank event log: append-only JSONL telemetry.

The one sink every telemetry producer writes to.  Each record is one
JSON object per line carrying the correlation tuple ``run_id`` /
``rank`` / ``step`` / ``wall_ms`` plus a ``kind`` from the closed set
{``step``, ``span``, ``counter``, ``fault``, ``ckpt``, ``collective``,
``summary``} and kind-specific fields (schema: docs/observability.md).

Design constraints (docs/observability.md):

- **Off by default.**  Nothing is created, opened, or timed unless
  ``MXTPU_TELEMETRY=1`` or ``MXTPU_TELEMETRY_DIR`` is set; the
  disabled :func:`emit` is one cached boolean check.
- **Off the step path.**  :func:`emit` appends a tuple to an in-memory
  buffer (no serialization, no IO); a background daemon thread
  serializes and writes every ``_FLUSH_INTERVAL_S``, or sooner when
  the buffer passes the high-water mark.  :func:`flush` forces a
  synchronous drain (tests, exit paths).
- **Bounded.**  The per-rank file rotates at ``MXTPU_TELEMETRY_MAX_MB``
  (one ``.1`` predecessor kept), so a runaway loop can never fill a
  pod's shared scratch.
- **Per-rank files.**  ``events-rank00042.jsonl`` under the telemetry
  dir; ranks never contend on one file, and the aggregator/mxtop merge
  by reading the directory.
"""
from __future__ import annotations

import atexit
import json
import os
import threading
import time

__all__ = ["enabled", "telemetry_dir", "run_id", "rank", "get",
           "refresh", "emit", "flush", "last_fault", "EventLog", "KINDS"]

#: the closed set of record kinds (docs/observability.md); "elastic"
#: records are the re-mesh agreement trail (propose/adopt/resume with
#: generation stamps — docs/resilience.md "Elasticity"); "serve"
#: records are one-per-dispatched-batch serving telemetry
#: (docs/serving.md — queue_wait/pack/device/unpack phases, occupancy,
#: padding waste, per-request latencies); "retrace" records are the
#: retrace sentry's attributed post-warmup lowerings (docs/perf.md,
#: observability/retrace.py — the divergent cache-key ingredient, the
#: requesting site, component diffs); "slo_alert" records are the live
#: SLO engine's burn-rate alert edges (observability/sloengine.py —
#: tier, fire/clear, per-window burns; flight-ring automatic like
#: every emit)
KINDS = ("step", "span", "counter", "fault", "ckpt", "collective",
         "summary", "elastic", "serve", "retrace", "slo_alert")

_FLUSH_INTERVAL_S = 1.0
_HIGH_WATER = 256            # buffered records that trigger an early flush

_TRUE = ("1", "true", "on", "yes")
_FALSE = ("0", "false", "off", "no", "")


def enabled():
    """Telemetry on?  ``MXTPU_TELEMETRY`` wins; setting only
    ``MXTPU_TELEMETRY_DIR`` also enables (the common launcher idiom)."""
    raw = os.environ.get("MXTPU_TELEMETRY")
    if raw is not None:
        return raw.strip().lower() in _TRUE
    return bool(os.environ.get("MXTPU_TELEMETRY_DIR"))


def telemetry_dir():
    """Directory holding the per-rank JSONL files."""
    return os.environ.get("MXTPU_TELEMETRY_DIR") or \
        os.path.join(os.getcwd(), "mxtpu_telemetry")


def rank():
    """This process's rank: launcher env first (valid before
    jax.distributed init), then jax, then 0."""
    raw = os.environ.get("MXTPU_WORKER_RANK")
    if raw:
        try:
            return int(raw)
        except ValueError:
            pass
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def _gen_run_id():
    return "%08x" % (int(time.time() * 1e3) ^ (os.getpid() << 16)
                     & 0xFFFFFFFF)


def run_id():
    """The run correlation id: ``MXTPU_RUN_ID`` (the launcher sets one
    id pod-wide) or a generated per-process hex stamp."""
    log = get()
    if log is not None:
        return log.run_id
    return os.environ.get("MXTPU_RUN_ID") or _gen_run_id()


def _max_bytes():
    try:
        mb = float(os.environ.get("MXTPU_TELEMETRY_MAX_MB", "64"))
    except ValueError:
        mb = 64.0
    return int(mb * 1024 * 1024)


class EventLog(object):
    """Buffered append-only JSONL writer for ONE rank.

    Use the module-level :func:`emit` in library code — it owns the
    process singleton and the enabled/disabled decision; construct an
    EventLog directly only in tests.
    """

    def __init__(self, directory, rank=0, run_id=None, max_bytes=None,
                 flush_interval_s=_FLUSH_INTERVAL_S,
                 high_water=_HIGH_WATER):
        self.directory = str(directory)
        self.rank = int(rank)
        self.run_id = run_id or os.environ.get("MXTPU_RUN_ID") \
            or _gen_run_id()
        self.max_bytes = _max_bytes() if max_bytes is None \
            else int(max_bytes)
        self.path = os.path.join(
            self.directory, "events-rank%05d.jsonl" % self.rank)
        self.flush_interval_s = flush_interval_s
        self.high_water = int(high_water)
        self.last_fault = None          # most recent fault record (dict)
        self._buf = []
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._fh = None
        self._thread = None
        os.makedirs(self.directory, exist_ok=True)

    # -- hot path ------------------------------------------------------
    def emit(self, kind, step=None, **fields):
        """Append one record.  No serialization, no IO — a tuple append
        plus a length check; the flusher thread does the rest."""
        # lock-free by design: list.append is GIL-atomic and flush()
        # drains via a single swap, so emitters never wait on json/IO
        self._buf.append(  # mxl: thread-shared-ok (MXL-Q001)
            (kind, step, time.time(), fields))
        if kind == "fault":
            self.last_fault = {"step": step, "wall_ms": None}
            self.last_fault.update(fields)
        if len(self._buf) >= self.high_water and not self._wake.is_set():
            self._wake.set()
        if self._thread is None:
            self._start_flusher()

    # -- flush machinery -----------------------------------------------
    def _start_flusher(self):
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name="mxtpu-telemetry-rank%d" % self.rank)
            self._thread.start()
        atexit.register(self.close)

    def _run(self):
        while not self._stop.is_set():
            self._wake.wait(self.flush_interval_s)
            self._wake.clear()
            try:
                self.flush()
            except Exception:            # telemetry must never kill a job
                return

    def _serialize(self, kind, step, ts, fields):
        rec = {"run_id": self.run_id, "rank": self.rank, "kind": kind,
               "step": step, "wall_ms": int(ts * 1000.0)}
        rec.update(fields)
        return json.dumps(rec, default=str, separators=(",", ":"))

    def flush(self):
        """Synchronously drain the buffer to disk (rotating first if
        the file has outgrown ``max_bytes``)."""
        # swap the buffer under the GIL; serialization happens on the
        # drained copy so emitters never wait on json/IO
        buf, self._buf = self._buf, []
        if not buf:
            return
        lines = "".join(self._serialize(*rec) + "\n" for rec in buf)
        with self._lock:
            self._maybe_rotate()
            if self._fh is None:
                self._fh = open(self.path, "a")
            self._fh.write(lines)
            self._fh.flush()

    def _maybe_rotate(self):
        if self.max_bytes <= 0:
            return
        try:
            size = self._fh.tell() if self._fh is not None \
                else os.path.getsize(self.path)
        except OSError:
            return
        if size < self.max_bytes:
            return
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        old = self.path + ".1"
        try:
            if os.path.exists(old):
                os.remove(old)           # keep ONE predecessor: bounded
            os.rename(self.path, old)
        except OSError:
            pass

    def close(self):
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)
        try:
            self.flush()
        finally:
            with self._lock:
                if self._fh is not None:
                    self._fh.close()
                    self._fh = None


# ----------------------------------------------------------------------
# process singleton — rebuilt whenever the env-derived key changes.
# The env probe is rate-limited (once per _RECHECK_S) so the per-emit
# fast path is one clock read + one dict lookup; code that flips
# MXTPU_TELEMETRY* at runtime and needs the change NOW (tests) calls
# :func:`refresh`.
# ----------------------------------------------------------------------
_STATE = {"log": None, "key": None, "checked": -1.0}
_RECHECK_S = 1.0


def _env_key():
    return (enabled(), os.environ.get("MXTPU_TELEMETRY_DIR"),
            os.environ.get("MXTPU_RUN_ID"))


def get():
    """The process EventLog, or None when telemetry is off."""
    now = time.monotonic()
    if 0.0 <= now - _STATE["checked"] < _RECHECK_S:
        return _STATE["log"]
    _STATE["checked"] = now
    key = _env_key()
    if _STATE["key"] != key:
        old = _STATE["log"]
        if old is not None:
            try:
                old.close()
            except Exception:
                pass
        _STATE["log"] = EventLog(telemetry_dir(), rank=rank()) \
            if key[0] else None
        _STATE["key"] = key
    return _STATE["log"]


def refresh():
    """Re-derive the singleton from the environment immediately
    (bypasses the rate-limited recheck in :func:`get`)."""
    _STATE["checked"] = -1.0
    return get()


def emit(kind, step=None, **fields):
    """Record one event iff telemetry is enabled (the library seam —
    cheap no-op otherwise).  Every call ALSO lands in the crash flight
    recorder's bounded ring (:mod:`.flight`) first — one deque append
    — so a postmortem dump has the recent event tail even when
    telemetry never wrote a file."""
    _flight.note(kind, step, fields)
    log = get()
    if log is not None:
        log.emit(kind, step=step, **fields)


def flush():
    """Force-drain the buffer (exit paths, tests, bench emit points)."""
    log = _STATE["log"]
    if log is not None:
        log.flush()


def last_fault():
    """The most recent fault record emitted by THIS process, or None —
    ranks include it in their published pod summaries."""
    log = _STATE["log"]
    return log.last_fault if log is not None else None


from . import flight as _flight  # noqa: E402  (bottom: flight's lazy
#                                 events imports resolve against the
#                                 fully-defined module above)
