"""Phase spans: one name shared by the event log and the xprof trace.

``span("data_wait")`` / ``span("h2d")`` / ``span("step")`` /
``span("allreduce")`` / ``span("ckpt_save")`` time a phase on the host
and (a) append a ``span`` record to the event log, (b) forward the same
name to :class:`mxnet_tpu.profiler.annotate` so a captured xprof trace
carries identical region names — the operator reads "allreduce is the
slow phase" off either surface without a translation table.

When telemetry is off and no profiler trace is running, ``span()``
returns a shared null context: zero allocation, zero timing.
"""
from __future__ import annotations

import time

from . import events

__all__ = ["span", "SPAN_NAMES", "timed_iter"]

#: canonical phase names (free-form names are allowed; these are the
#: ones the built-in wiring emits and mxtop groups by)
SPAN_NAMES = ("data_wait", "h2d", "step", "allreduce", "kv_barrier",
              "ckpt_save", "eval")


class _NullSpan(object):
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span(object):
    __slots__ = ("name", "step", "fields", "_t0", "_ann")

    def __init__(self, name, step, fields):
        self.name = name
        self.step = step
        self.fields = fields
        self._t0 = None
        self._ann = None

    def __enter__(self):
        try:
            from ..profiler import annotate
            self._ann = annotate(self.name)
            self._ann.__enter__()
        except Exception:               # no jax / exotic backend: host
            self._ann = None            # timing still works
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur_ms = (time.perf_counter() - self._t0) * 1e3
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            except Exception:
                pass
        events.emit("span", step=self.step, name=self.name,
                    dur_ms=round(dur_ms, 3), **self.fields)
        return False


def span(name, step=None, **fields):
    """Context manager timing one phase.  Null (free) when telemetry is
    off; otherwise records a ``span`` event and annotates the trace."""
    if events.get() is None:
        return _NULL
    return _Span(name, step, fields)


def timed_iter(iterable, name="data_wait", step_from=None):
    """Pass-through generator that times each ``next()`` under ``span``
    — the input-pipeline wait the fit loops can't see otherwise.  Plain
    iteration (no timing) when telemetry is off.

    ``step_from``: optional zero-arg callable giving the step to tag
    each span with (called per batch, AFTER the fetch).
    """
    if events.get() is None:
        for item in iterable:
            yield item
        return
    it = iter(iterable)
    while True:
        t0 = time.perf_counter()
        try:
            item = next(it)
        except StopIteration:
            return
        dur_ms = (time.perf_counter() - t0) * 1e3
        events.emit("span", name=name,
                    step=step_from() if step_from is not None else None,
                    dur_ms=round(dur_ms, 3))
        yield item
