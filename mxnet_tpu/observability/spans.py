"""Phase spans: one name shared by the event log and the xprof trace.

``span("data_wait")`` / ``span("h2d")`` / ``span("step")`` /
``span("allreduce")`` / ``span("ckpt_save")`` time a phase on the host
and (a) append a ``span`` record to the event log, (b) forward the same
name to :class:`mxnet_tpu.profiler.annotate` so a captured xprof trace
carries identical region names — the operator reads "allreduce is the
slow phase" off either surface without a translation table.

When telemetry is off and no profiler trace is running, ``span()``
returns a shared null context: zero allocation, zero timing.
"""
from __future__ import annotations

import time

from . import events
from . import trace as _trace
from .phases import TRAIN_PHASES

__all__ = ["span", "SPAN_NAMES", "timed_iter", "overlap_report"]

#: canonical phase names (free-form names are allowed; these are the
#: ones the built-in wiring emits and mxtop groups by).  Compat alias
#: for the shared registry — the ONE definition lives in
#: :mod:`.phases` so spans / profiler.annotate / parse_log columns
#: can't drift.
SPAN_NAMES = TRAIN_PHASES


class _NullSpan(object):
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span(object):
    __slots__ = ("name", "step", "fields", "_t0", "_ann", "_ids")

    def __init__(self, name, step, fields):
        self.name = name
        self.step = step
        self.fields = fields
        self._t0 = None
        self._ann = None
        self._ids = None

    def __enter__(self):
        try:
            from ..profiler import annotate
            self._ann = annotate(self.name)
            self._ann.__enter__()
        except Exception:               # no jax / exotic backend: host
            self._ann = None            # timing still works
        # MXTPU_TRACE=1: push a trace frame so this span carries
        # trace/span/parent ids and emits inside it bind to it
        self._ids = _trace.begin_span(self.name) or None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur_ms = (time.perf_counter() - self._t0) * 1e3
        if self._ids is not None:
            _trace.end_span()
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            except Exception:
                pass
        events.emit("span", step=self.step, name=self.name,
                    dur_ms=round(dur_ms, 3), **(self._ids or {}),
                    **self.fields)
        return False


def span(name, step=None, **fields):
    """Context manager timing one phase.  Null (free) when telemetry is
    off; otherwise records a ``span`` event and annotates the trace."""
    if events.get() is None:
        return _NULL
    return _Span(name, step, fields)


def timed_iter(iterable, name="data_wait", step_from=None):
    """Pass-through generator that times each ``next()`` under ``span``
    — the input-pipeline wait the fit loops can't see otherwise.  Plain
    iteration (no timing) when telemetry is off.

    ``step_from``: optional zero-arg callable giving the step to tag
    each span with (called per batch, AFTER the fetch).
    """
    if events.get() is None:
        for item in iterable:
            yield item
        return
    it = iter(iterable)
    while True:
        ids = _trace.begin_span(name)
        t0 = time.perf_counter()
        try:
            item = next(it)
        except StopIteration:
            if ids:
                _trace.end_span()
            return
        dur_ms = (time.perf_counter() - t0) * 1e3
        if ids:
            _trace.end_span()
        events.emit("span", name=name,
                    step=step_from() if step_from is not None else None,
                    dur_ms=round(dur_ms, 3), **ids)
        yield item


def overlap_report(records, phases=("data_wait", "h2d")):
    """Did the async machinery actually overlap?  From merged event
    records (:func:`..aggregate.read_events` output, or any list of
    record dicts), compute per-rank and pod-wide::

        overlap_ratio = serial_ms / wall_ms

    where ``serial_ms`` sums every ``phases`` span PLUS every ``step``
    record's duration inside the steady-state window, and ``wall_ms``
    is the elapsed wall clock between the rank's first and last
    ``step`` record.  The first step record bounds the window but is
    excluded from the sums, so compile time never pollutes the ratio.

    Serial execution: phases and steps tile the wall exactly, ratio
    ≈ 1.0 (slightly below — metric/callback time belongs to no phase).
    With the async feed on, the producer thread's ``data_wait``/``h2d``
    spans run DURING device compute, the same host time is counted in
    two phases, and the ratio rises above 1 — "wall < Σ phases" is the
    proof the dead time went under the step.  ``phases`` deliberately
    excludes ``allreduce``/``kv_barrier``: those spans nest inside the
    ``step`` record's window and would double-count serially.

    Returns ``{"overlap_ratio", "wall_ms", "serial_ms", "steps",
    "phase_ms": {phase: total}, "phase_p50_ms": {phase: p50},
    "per_rank": {rank: {...same shape...}}}``; ratios are None when a
    rank has fewer than two step records.
    """
    per_rank_events = {}
    for rec in records:
        if not isinstance(rec, dict):
            continue
        kind = rec.get("kind")
        if kind not in ("span", "step"):
            continue
        per_rank_events.setdefault(rec.get("rank") or 0, []).append(rec)

    def _p50(vals):
        vals = sorted(vals)
        n = len(vals)
        if not n:
            return None
        mid = n // 2
        return vals[mid] if n % 2 else 0.5 * (vals[mid - 1] + vals[mid])

    per_rank = {}
    tot_wall = tot_serial = tot_steps = 0.0
    pod_phase = {}
    pod_phase_durs = {}
    for rank, recs in sorted(per_rank_events.items()):
        steps = [r for r in recs if r.get("kind") == "step"
                 and r.get("wall_ms") is not None
                 and r.get("dur_ms") is not None]
        steps.sort(key=lambda r: r["wall_ms"])
        entry = {"overlap_ratio": None, "wall_ms": None, "serial_ms": None,
                 "steps": len(steps), "phase_ms": {}, "phase_p50_ms": {}}
        per_rank[rank] = entry
        if len(steps) < 2:
            continue
        t0, t1 = steps[0]["wall_ms"], steps[-1]["wall_ms"]
        wall = float(t1) - float(t0)
        if wall <= 0:
            continue
        serial = sum(float(r["dur_ms"]) for r in steps[1:])
        phase_durs = {}
        for r in recs:
            if r.get("kind") != "span" or r.get("name") not in phases:
                continue
            w = r.get("wall_ms")
            if w is None or not (t0 < w <= t1):
                continue
            d = float(r.get("dur_ms") or 0.0)
            serial += d
            phase_durs.setdefault(r["name"], []).append(d)
        entry.update(
            wall_ms=round(wall, 3), serial_ms=round(serial, 3),
            overlap_ratio=round(serial / wall, 4),
            phase_ms={k: round(sum(v), 3)
                      for k, v in sorted(phase_durs.items())},
            phase_p50_ms={k: round(_p50(v), 3)
                          for k, v in sorted(phase_durs.items())})
        tot_wall += wall
        tot_serial += serial
        tot_steps += len(steps)
        for k, v in phase_durs.items():
            pod_phase[k] = pod_phase.get(k, 0.0) + sum(v)
            pod_phase_durs.setdefault(k, []).extend(v)
    return {
        "overlap_ratio": round(tot_serial / tot_wall, 4) if tot_wall else None,
        "wall_ms": round(tot_wall, 3) if tot_wall else None,
        "serial_ms": round(tot_serial, 3) if tot_wall else None,
        "steps": int(tot_steps),
        "phase_ms": {k: round(v, 3) for k, v in sorted(pod_phase.items())},
        "phase_p50_ms": {k: round(_p50(v), 3)
                         for k, v in sorted(pod_phase_durs.items())},
        "per_rank": per_rank,
    }
