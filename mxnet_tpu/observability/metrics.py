"""Live metrics: mergeable quantile sketches, typed instruments, and
time-windowed rollups behind one process registry.

The percentile math everywhere else in the tree (``counters.percentile``
over a raw-sample deque, the per-replica ``_lat`` lists in
``serving.telemetry``) cannot be combined exactly: averaging per-rank
p95s is not a pod p95, and a 512-sample window forgets the tail under
load.  This module replaces the raw lists with a **relative-error
log-bucketed quantile sketch** (the DDSketch construction):

- **Bounded memory.**  Samples land in geometrically-spaced buckets
  (``gamma = (1+alpha)/(1-alpha)``); six orders of magnitude of values
  at the default ``alpha = 0.01`` occupy ~700 buckets of one integer
  each, independent of sample count.
- **Relative-error guarantee.**  Any quantile estimate ``q̂`` of a true
  value ``q`` satisfies ``|q̂ - q| <= alpha * q``.
- **Exact associative merge.**  Merging adds integer bucket counts, so
  ``merge(a, merge(b, c)) == merge(merge(a, b), c)`` and a merge of N
  per-replica sketches yields **bit-identical quantiles** to one sketch
  fed the concatenated stream — the property pod/fleet rollups rely on
  (quantiles depend only on bucket counts and the bucket->value map,
  never on float-summation order).
- **Deterministic serialization.**  ``to_json`` emits sorted compact
  JSON, so equal sketches serialize to equal bytes (content-hashable,
  diffable across ranks).

On top of the sketch sit the typed instruments (:class:`Counter`
monotone, :class:`Gauge` set-or-callback, :class:`Histogram`
sketch-backed) and the :class:`MetricsRegistry` every producer feeds
(``serving.telemetry.emit_batch``, ``counters.StepStats``, the fleet
router).  A histogram additionally keeps a **time-windowed ring** of
per-slot sketches: the windows named by ``MXTPU_METRICS_WINDOWS``
(default ``10,60,300,3600`` seconds) are answered by merging ring slots
— aggregation by sketch-merge, never by re-sampling — which is what
the SLO engine's burn rates (:mod:`.sloengine`) read.

``render_prometheus`` serializes the registry in the Prometheus text
exposition format for the ``GET /metrics`` doors on ``mxserve`` and
``mxfleet serve``; ``parse_prometheus`` is the matching tolerant reader
(``mxtop --watch``, the CI scrape smoke).
"""
from __future__ import annotations

import json
import math
import os
import threading

__all__ = ["QuantileSketch", "Counter", "Gauge", "Histogram",
           "MetricsRegistry", "registry", "reset_registry", "windows",
           "render_prometheus", "parse_prometheus",
           "DEFAULT_ALPHA", "DEFAULT_WINDOWS"]

DEFAULT_ALPHA = 0.01
#: rollup horizons (seconds) when MXTPU_METRICS_WINDOWS is unset
DEFAULT_WINDOWS = (10, 60, 300, 3600)

#: bucket-count ceiling.  At alpha=0.01 six orders of magnitude span
#: ~690 buckets, so the default never collapses in practice — which is
#: what keeps the merge bit-identity guarantee unconditional; collapse
#: (lowest keys fold together) only exists as a runaway backstop.
_MAX_BUCKETS = 4096


def windows(raw=None):
    """The configured rollup horizons, ascending: parse
    ``MXTPU_METRICS_WINDOWS`` (comma-separated seconds) or fall back to
    :data:`DEFAULT_WINDOWS`.  Bad entries are dropped, not fatal."""
    raw = raw if raw is not None \
        else os.environ.get("MXTPU_METRICS_WINDOWS")
    if not raw:
        return tuple(DEFAULT_WINDOWS)
    out = []
    for part in str(raw).replace(";", ",").split(","):
        part = part.strip()
        if not part:
            continue
        try:
            val = int(float(part))
        except ValueError:
            continue
        if val > 0:
            out.append(val)
    return tuple(sorted(set(out))) or tuple(DEFAULT_WINDOWS)


class QuantileSketch(object):
    """Relative-error log-bucketed quantile sketch (DDSketch family).

    ``add`` is the hot call: one ``log``, one dict increment.  Values
    land in bucket ``ceil(log_gamma(v))`` and are estimated at the
    bucket midpoint ``2 * gamma^key / (gamma + 1)``, which bounds the
    relative error by ``alpha``.  Negative values mirror into a
    separate key space; exact zeros get their own counter (log-space
    buckets cannot represent 0).
    """

    __slots__ = ("alpha", "gamma", "_lg", "max_buckets", "buckets",
                 "neg_buckets", "zero", "count", "total", "min", "max")

    def __init__(self, alpha=DEFAULT_ALPHA, max_buckets=_MAX_BUCKETS):
        if not 0.0 < alpha < 1.0:
            raise ValueError("alpha must be in (0, 1): %r" % (alpha,))
        self.alpha = float(alpha)
        self.gamma = (1.0 + self.alpha) / (1.0 - self.alpha)
        self._lg = math.log(self.gamma)
        self.max_buckets = int(max_buckets)
        self.buckets = {}        # key -> int count (positive values)
        self.neg_buckets = {}    # key -> int count (abs of negatives)
        self.zero = 0
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None

    # -- ingest --------------------------------------------------------
    def _key(self, mag):
        return int(math.ceil(math.log(mag) / self._lg))

    def add(self, value, count=1):
        value = float(value)
        count = int(count)
        if count <= 0 or value != value:      # drop NaN, non-positive n
            return
        if value == 0.0:
            self.zero += count
        elif value > 0.0:
            key = self._key(value)
            self.buckets[key] = self.buckets.get(key, 0) + count
        else:
            key = self._key(-value)
            self.neg_buckets[key] = self.neg_buckets.get(key, 0) + count
        self.count += count
        self.total += value * count
        self.min = value if self.min is None else min(self.min, value)
        self.max = value if self.max is None else max(self.max, value)
        if len(self.buckets) > self.max_buckets:
            self._collapse(self.buckets)
        if len(self.neg_buckets) > self.max_buckets:
            self._collapse(self.neg_buckets)

    def extend(self, values):
        for v in values:
            self.add(v)
        return self

    @staticmethod
    def _collapse(buckets):
        """Runaway backstop: fold the two lowest keys together.  Never
        reached under the default alpha/max_buckets pairing."""
        lo = sorted(buckets)[:2]
        if len(lo) == 2:
            buckets[lo[1]] += buckets.pop(lo[0])

    # -- merge ---------------------------------------------------------
    def merge(self, other):
        """Fold ``other`` into self.  Integer bucket addition — exact,
        associative, commutative; quantiles of the merge are
        bit-identical to quantiles of the concatenated stream."""
        if other is None or other.count == 0:
            return self
        if abs(other.alpha - self.alpha) > 1e-12:
            raise ValueError("cannot merge sketches with different "
                             "alpha (%g vs %g)" % (self.alpha,
                                                   other.alpha))
        for key, n in other.buckets.items():
            self.buckets[key] = self.buckets.get(key, 0) + n
        for key, n in other.neg_buckets.items():
            self.neg_buckets[key] = self.neg_buckets.get(key, 0) + n
        self.zero += other.zero
        self.count += other.count
        self.total += other.total
        for attr, fn in (("min", min), ("max", max)):
            mine, theirs = getattr(self, attr), getattr(other, attr)
            setattr(self, attr, theirs if mine is None
                    else (mine if theirs is None else fn(mine, theirs)))
        return self

    @classmethod
    def merged(cls, sketches):
        """A fresh sketch folding every sketch in ``sketches``."""
        sketches = [s for s in sketches if s is not None]
        if not sketches:
            return cls()
        out = cls(alpha=sketches[0].alpha,
                  max_buckets=sketches[0].max_buckets)
        for s in sketches:
            out.merge(s)
        return out

    # -- query ---------------------------------------------------------
    def _value_of(self, key):
        return 2.0 * math.exp(key * self._lg) / (self.gamma + 1.0)

    def quantile(self, q):
        """The q-quantile estimate (``q`` in [0, 1]), or None when
        empty.  Deterministic: depends only on bucket counts, so equal
        bucket contents always answer equal values."""
        if self.count == 0:
            return None
        q = min(1.0, max(0.0, float(q)))
        rank = q * (self.count - 1)
        cum = 0
        # order: negatives (most negative first), zeros, positives
        for key in sorted(self.neg_buckets, reverse=True):
            cum += self.neg_buckets[key]
            if cum > rank:
                return -self._value_of(key)
        cum += self.zero
        if cum > rank:
            return 0.0
        for key in sorted(self.buckets):
            cum += self.buckets[key]
            if cum > rank:
                return self._value_of(key)
        return self._value_of(max(self.buckets)) if self.buckets \
            else self.max

    def percentile(self, pct):
        return self.quantile(float(pct) / 100.0)

    def mean(self):
        return self.total / self.count if self.count else None

    def count_above(self, threshold):
        """Samples strictly above ``threshold`` — the burn-rate
        numerator.  Counted whole-bucket: a bucket is "above" when its
        estimate exceeds the threshold, so the answer is deterministic
        and merge-stable."""
        threshold = float(threshold)
        n = 0
        if threshold < 0.0:
            n += self.zero
            n += sum(self.buckets.values())
            for key, c in self.neg_buckets.items():
                if -self._value_of(key) > threshold:
                    n += c
            return n
        for key, c in self.buckets.items():
            if self._value_of(key) > threshold:
                n += c
        return n

    # -- serialization -------------------------------------------------
    def to_dict(self):
        """Compact JSON-able form.  Keys sorted at the json layer; the
        float fields round-trip via repr so deserialize(serialize(s))
        is bit-identical."""
        out = {"a": self.alpha, "n": self.count, "z": self.zero,
               "s": self.total,
               "b": {str(k): v for k, v in self.buckets.items()}}
        if self.neg_buckets:
            out["nb"] = {str(k): v for k, v in self.neg_buckets.items()}
        if self.min is not None:
            out["lo"], out["hi"] = self.min, self.max
        return out

    def to_json(self):
        return json.dumps(self.to_dict(), sort_keys=True,
                          separators=(",", ":"))

    @classmethod
    def from_dict(cls, doc):
        if not isinstance(doc, dict) or "a" not in doc:
            return None
        sk = cls(alpha=float(doc["a"]))
        sk.count = int(doc.get("n") or 0)
        sk.zero = int(doc.get("z") or 0)
        sk.total = float(doc.get("s") or 0.0)
        sk.buckets = {int(k): int(v)
                      for k, v in (doc.get("b") or {}).items()}
        sk.neg_buckets = {int(k): int(v)
                          for k, v in (doc.get("nb") or {}).items()}
        if doc.get("lo") is not None:
            sk.min = float(doc["lo"])
            sk.max = float(doc["hi"])
        return sk

    @classmethod
    def from_json(cls, raw):
        try:
            return cls.from_dict(json.loads(raw))
        except (ValueError, TypeError):
            return None

    def __len__(self):
        return self.count

    def __repr__(self):
        return ("QuantileSketch(n=%d, p50=%s, p95=%s)"
                % (self.count, self.quantile(0.5), self.quantile(0.95)))


# ----------------------------------------------------------------------
# typed instruments
# ----------------------------------------------------------------------
class Counter(object):
    """Monotone counter.  ``inc`` only; a decrement is a bug."""

    __slots__ = ("name", "help", "labels", "_value", "_lock")

    def __init__(self, name, help="", labels=None):
        self.name = name
        self.help = help
        self.labels = labels or {}
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n=1):
        if n < 0:
            raise ValueError("counter %s cannot decrease" % self.name)
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value


class Gauge(object):
    """Point-in-time value: ``set`` it, or construct with ``fn`` and it
    is polled at render/read time (queue depths, lease state)."""

    __slots__ = ("name", "help", "labels", "_value", "_fn")

    def __init__(self, name, help="", labels=None, fn=None):
        self.name = name
        self.help = help
        self.labels = labels or {}
        self._value = 0.0
        self._fn = fn

    def set(self, v):
        self._value = float(v)  # mxl: thread-shared-ok (MXL-Q001)

    @property
    def value(self):
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:
                return self._value
        return self._value


class Histogram(object):
    """Sketch-backed distribution with a time-windowed ring.

    ``observe`` feeds (a) the cumulative sketch — the whole-process
    distribution the Prometheus summary renders — and (b) the current
    ring slot.  ``window_sketch(seconds, now)`` answers a horizon by
    merging the slots inside it: rollup by sketch-merge, so a 5m window
    IS the exact union of its 10s slots.  Slot width is the smallest
    configured window; ring length covers the largest.
    """

    __slots__ = ("name", "help", "labels", "alpha", "cumulative",
                 "windows", "slot_s", "_slots", "_nslots", "_lock")

    def __init__(self, name, help="", labels=None, alpha=DEFAULT_ALPHA,
                 windows_s=None):
        self.name = name
        self.help = help
        self.labels = labels or {}
        self.alpha = float(alpha)
        self.windows = tuple(windows_s) if windows_s else windows()
        self.slot_s = max(1, int(self.windows[0]))
        self._nslots = max(2, int(self.windows[-1] // self.slot_s) + 1)
        self.cumulative = QuantileSketch(alpha=self.alpha)
        self._slots = {}         # slot id -> QuantileSketch
        self._lock = threading.Lock()

    def observe(self, value, now=None):
        import time as _t
        now = _t.time() if now is None else float(now)
        slot = int(now // self.slot_s)
        with self._lock:
            self.cumulative.add(value)
            sk = self._slots.get(slot)
            if sk is None:
                sk = self._slots[slot] = QuantileSketch(alpha=self.alpha)
                floor = slot - self._nslots
                for sid in [s for s in self._slots if s <= floor]:
                    del self._slots[sid]
            sk.add(value)

    def window_sketch(self, seconds, now=None):
        """Merged sketch of every sample in the last ``seconds``."""
        import time as _t
        now = _t.time() if now is None else float(now)
        slot = int(now // self.slot_s)
        # every slot intersecting [now - seconds, now] — may over-cover
        # by up to one slot width at the old edge, never under-cover
        first = int((now - float(seconds)) // self.slot_s)
        with self._lock:
            picks = [sk for sid, sk in self._slots.items()
                     if first <= sid <= slot]
        return QuantileSketch.merged(picks)

    def snapshot(self, now=None):
        """JSON-able view: cumulative quantiles + per-window counts and
        p95s (what mxtop's SLO pane and /metrics windows render)."""
        with self._lock:
            cum = QuantileSketch.merged([self.cumulative])
        out = {"count": cum.count, "sum": cum.total,
               "p50": cum.quantile(0.5), "p95": cum.quantile(0.95),
               "p99": cum.quantile(0.99), "windows": {}}
        for w in self.windows:
            sk = self.window_sketch(w, now=now)
            out["windows"][str(w)] = {"count": sk.count,
                                      "p95": sk.quantile(0.95)}
        return out


# ----------------------------------------------------------------------
# registry
# ----------------------------------------------------------------------
def _label_key(labels):
    return tuple(sorted((labels or {}).items()))


class MetricsRegistry(object):
    """The process-wide instrument table.  ``counter``/``gauge``/
    ``histogram`` are get-or-create (idempotent per (name, labels)), so
    producers never coordinate instrument construction."""

    def __init__(self):
        self._instruments = {}   # (name, label items) -> instrument
        self._lock = threading.Lock()

    def _get(self, cls, name, help, labels, **kw):
        key = (name, _label_key(labels))
        inst = self._instruments.get(key)
        if inst is not None:
            return inst
        with self._lock:
            inst = self._instruments.get(key)
            if inst is None:
                inst = cls(name, help=help, labels=labels, **kw)
                self._instruments[key] = inst
            return inst

    def counter(self, name, help="", labels=None):
        return self._get(Counter, name, help, labels)

    def gauge(self, name, help="", labels=None, fn=None):
        g = self._get(Gauge, name, help, labels)
        if fn is not None:
            g._fn = fn           # late-bound callback wins
        return g

    def histogram(self, name, help="", labels=None,
                  alpha=DEFAULT_ALPHA, windows_s=None):
        return self._get(Histogram, name, help, labels, alpha=alpha,
                         windows_s=windows_s)

    def instruments(self):
        with self._lock:
            return sorted(self._instruments.values(),
                          key=lambda i: (i.name, _label_key(i.labels)))

    def find(self, name, labels=None):
        return self._instruments.get((name, _label_key(labels)))

    def histograms(self, name=None):
        return [i for i in self.instruments()
                if isinstance(i, Histogram)
                and (name is None or i.name == name)]

    def snapshot(self, now=None):
        """Flat JSON-able dump (debug door / tests)."""
        out = {}
        for inst in self.instruments():
            key = inst.name
            if inst.labels:
                key += "{%s}" % ",".join(
                    "%s=%s" % kv for kv in _label_key(inst.labels))
            if isinstance(inst, Histogram):
                out[key] = inst.snapshot(now=now)
            else:
                out[key] = inst.value
        return out


_REGISTRY = {"reg": None}


def registry():
    """The process MetricsRegistry singleton."""
    if _REGISTRY["reg"] is None:
        _REGISTRY["reg"] = MetricsRegistry()
    return _REGISTRY["reg"]


def reset_registry():
    """Drop the singleton (tests)."""
    _REGISTRY["reg"] = None


def exposition_enabled():
    """``MXTPU_METRICS`` gates the HTTP /metrics doors (default on —
    the registry itself always exists; only exposition is toggled)."""
    raw = os.environ.get("MXTPU_METRICS", "1").strip().lower()
    return raw not in ("0", "false", "off", "no")


# ----------------------------------------------------------------------
# Prometheus text exposition
# ----------------------------------------------------------------------
def _prom_labels(labels, extra=None):
    items = list(_label_key(labels))
    if extra:
        items += list(sorted(extra.items()))
    if not items:
        return ""
    return "{%s}" % ",".join(
        '%s="%s"' % (k, str(v).replace('"', '\\"')) for k, v in items)


def _prom_num(v):
    if v is None:
        return "NaN"
    f = float(v)
    return repr(f) if f != int(f) else str(int(f))


def render_prometheus(reg=None, now=None):
    """The registry in the Prometheus text exposition format.

    Counters/gauges render as single samples; histograms render as a
    summary (``{quantile="0.5|0.95|0.99"}`` + ``_count``/``_sum`` from
    the cumulative sketch) plus per-window p95 gauges
    (``<name>_window{window="60"}``) so the scrape carries the same
    horizons the SLO engine evaluates.
    """
    reg = reg or registry()
    lines = []
    seen_meta = set()
    for inst in reg.instruments():
        name = inst.name
        if isinstance(inst, Counter):
            if name not in seen_meta:
                seen_meta.add(name)
                if inst.help:
                    lines.append("# HELP %s %s" % (name, inst.help))
                lines.append("# TYPE %s counter" % name)
            lines.append("%s%s %s" % (name, _prom_labels(inst.labels),
                                      _prom_num(inst.value)))
        elif isinstance(inst, Gauge):
            if name not in seen_meta:
                seen_meta.add(name)
                if inst.help:
                    lines.append("# HELP %s %s" % (name, inst.help))
                lines.append("# TYPE %s gauge" % name)
            lines.append("%s%s %s" % (name, _prom_labels(inst.labels),
                                      _prom_num(inst.value)))
        elif isinstance(inst, Histogram):
            if name not in seen_meta:
                seen_meta.add(name)
                if inst.help:
                    lines.append("# HELP %s %s" % (name, inst.help))
                lines.append("# TYPE %s summary" % name)
            cum = inst.cumulative
            for q in (0.5, 0.95, 0.99):
                lines.append("%s%s %s" % (
                    name,
                    _prom_labels(inst.labels, {"quantile": "%g" % q}),
                    _prom_num(cum.quantile(q))))
            lines.append("%s_count%s %d" % (
                name, _prom_labels(inst.labels), cum.count))
            lines.append("%s_sum%s %s" % (
                name, _prom_labels(inst.labels), _prom_num(cum.total)))
            for w in inst.windows:
                sk = inst.window_sketch(w, now=now)
                lines.append("%s_window%s %s" % (
                    name,
                    _prom_labels(inst.labels,
                                 {"window": str(w), "quantile": "0.95"}),
                    _prom_num(sk.quantile(0.95))))
    return "\n".join(lines) + "\n"


def parse_prometheus(text):
    """Tolerant reader for the text format: ``[(name, labels, value)]``.
    Skips comments and malformed lines rather than raising — the shape
    ``mxtop --watch`` and the CI scrape smoke consume."""
    out = []
    for line in (text or "").splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            metric, raw_val = line.rsplit(None, 1)
        except ValueError:
            continue
        labels = {}
        name = metric
        if "{" in metric and metric.endswith("}"):
            name, _, blob = metric.partition("{")
            for pair in blob[:-1].split(","):
                if "=" not in pair:
                    continue
                k, _, v = pair.partition("=")
                labels[k.strip()] = v.strip().strip('"')
        try:
            value = float(raw_val)
        except ValueError:
            continue
        out.append((name, labels, value))
    return out
