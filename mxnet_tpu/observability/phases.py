"""Canonical phase-name registry: the ONE place a phase is named.

Before this module, the training phase names lived in
``spans.SPAN_NAMES``, the serving phase names were implicit in the
``serve`` record's ``*_ms`` field names, and ``tools/parse_log.py``
re-derived its column names from both — three copies that could (and
in review almost did) drift.  Everything now imports from here:

- :mod:`.spans` re-exports :data:`TRAIN_PHASES` as ``SPAN_NAMES``
  (compat alias) and the fit/trainer/kvstore wiring uses the named
  constants,
- :mod:`mxnet_tpu.profiler` exposes the same :data:`PHASES` so an
  xprof region name and an event-log span name can never disagree,
- :mod:`mxnet_tpu.serving.telemetry` derives its per-phase ``*_ms``
  fields from :data:`SERVE_PHASES`,
- ``tools/parse_log.py`` builds its serve phase columns from the same
  tuple.

Free-form span names remain legal everywhere (``span("my_phase")``
works); the registry fixes the *built-in* names, it does not close the
namespace.
"""
from __future__ import annotations

__all__ = ["TRAIN_PHASES", "SERVE_PHASES", "PHASES", "is_canonical",
           "DATA_WAIT", "H2D", "STEP", "ALLREDUCE", "KV_BARRIER",
           "CKPT_SAVE", "EVAL", "HOTSTATE_SNAPSHOT", "WARM_RESUME",
           "QUEUE_WAIT", "PACK", "DEVICE", "UNPACK"]

#: phases the training wiring emits (fit loops, ShardedTrainer, kvstore,
#: and the warm-elasticity transition: host offload + warm assembly)
TRAIN_PHASES = ("data_wait", "h2d", "step", "allreduce", "kv_barrier",
                "ckpt_save", "eval", "hotstate_snapshot", "warm_resume")

#: request-visible serving phases, in pipeline order (docs/serving.md)
SERVE_PHASES = ("queue_wait", "pack", "device", "unpack")

#: every built-in phase name, training first then serving
PHASES = TRAIN_PHASES + SERVE_PHASES

(DATA_WAIT, H2D, STEP, ALLREDUCE, KV_BARRIER, CKPT_SAVE, EVAL,
 HOTSTATE_SNAPSHOT, WARM_RESUME) = TRAIN_PHASES
(QUEUE_WAIT, PACK, DEVICE, UNPACK) = SERVE_PHASES

_CANON = frozenset(PHASES)


def is_canonical(name):
    """Is ``name`` one of the built-in phase names?"""
    return name in _CANON
