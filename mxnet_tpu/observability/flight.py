"""Crash flight recorder: always-on ring buffer + pending-collective
ledger, dumped at the crash seams.

The PyTorch collective-flight-recorder shape, grown for this tree's
failure mode: a pod wedges in an allreduce, the watchdog fires exit 3,
and the postmortem question is *which rank never launched seq K* — but
``MXTPU_TELEMETRY`` was off, so there is no event log.  This module is
the always-on answer:

- a bounded in-memory ring of the last ``MXTPU_FLIGHT_DEPTH`` (default
  512, ``0`` disables) events — every record that flows through
  :func:`events.emit` and :func:`observability.record_step` lands here
  FIRST, before (and regardless of) the telemetry-enabled check.  One
  ``deque.append`` of a tuple: allocation-bounded, no locks, no IO.
- a pending-collective ledger: :func:`collective_begin` records (op,
  seq, participants, launch wall time) when a collective is handed to
  the runtime, :func:`collective_end` retires it.  A hung collective is
  exactly an entry that never retired.
- :func:`dump`: serialize ring + ledger (+ a best-effort liveness probe
  naming the absent ranks) to ``MXTPU_TELEMETRY_DIR`` or a tmp
  fallback.  Wired into every crash seam: watchdog timeout/stall,
  sentinel escalation, ``exit_for_restart``/``exit_for_remesh``, the
  ResilienceError excepthook, and SIGTERM.

The ring records the same tuples :class:`events.EventLog` buffers, so
a dump reads like a tail of the event log even for runs that never had
one.
"""
from __future__ import annotations

import collections
import json
import os
import signal
import sys
import tempfile
import threading
import time
import traceback

from . import trace as _trace

__all__ = ["depth", "get", "reset", "note", "collective_begin",
           "collective_end", "pending_collectives", "dump",
           "set_liveness_probe", "dump_dir", "thread_stacks",
           "FlightRecorder"]

_DEFAULT_DEPTH = 512


def depth():
    """``MXTPU_FLIGHT_DEPTH``: ring capacity in events (default 512;
    ``0`` disables the recorder entirely)."""
    raw = os.environ.get("MXTPU_FLIGHT_DEPTH", "")
    try:
        return int(raw) if raw.strip() else _DEFAULT_DEPTH
    except ValueError:
        return _DEFAULT_DEPTH


def dump_dir():
    """Where dumps land: the telemetry dir when one is configured
    (even with ``MXTPU_TELEMETRY=0`` — the operator named a scratch
    path; use it), else a per-user tmp fallback that needs no setup."""
    configured = os.environ.get("MXTPU_TELEMETRY_DIR")
    if configured:
        return configured
    from . import events
    if events.enabled():
        return events.telemetry_dir()
    return os.path.join(tempfile.gettempdir(), "mxtpu-flight")


def thread_stacks():
    """Every live thread's current frames — the "who is holding the
    wedged lock" half of a watchdog postmortem.  Pairs
    ``sys._current_frames()`` with ``threading.enumerate()`` so each
    stack carries the thread's name/daemon flag; threads the interpreter
    knows but :mod:`threading` doesn't (C-spawned) appear by ident
    only."""
    frames = sys._current_frames()
    by_ident = {t.ident: t for t in threading.enumerate()}
    out = []
    for ident, frame in frames.items():
        t = by_ident.get(ident)
        out.append({
            "name": t.name if t is not None else "<non-python>",
            "ident": ident,
            "daemon": bool(t.daemon) if t is not None else None,
            "current": ident == threading.get_ident(),
            "stack": "".join(traceback.format_stack(frame)),
        })
    out.sort(key=lambda rec: (not rec["current"], rec["name"]))
    return out


class FlightRecorder(object):
    """Ring + ledger for ONE process (use the module-level functions in
    library code; construct directly only in tests)."""

    def __init__(self, depth=_DEFAULT_DEPTH):
        self.depth = int(depth)
        self._ring = collections.deque(maxlen=max(self.depth, 1))
        self._pending = {}          # (op, seq) -> ledger entry
        self._lock = threading.Lock()
        self._probe = None          # zero-arg -> absent rank list
        self.dumps = 0

    # -- hot path (one thread-safe deque append) -----------------------
    def note(self, kind, step, fields):
        self._ring.append((time.time(), kind, step, fields))

    # -- collective ledger ---------------------------------------------
    def collective_begin(self, op, seq, participants=None, **fields):
        entry = {"op": op, "seq": seq, "launch_wall_ms":
                 int(time.time() * 1000.0)}
        if participants is not None:
            entry["participants"] = list(participants)
        entry.update(fields)
        with self._lock:
            self._pending[(op, seq)] = entry
        return entry

    def collective_end(self, op, seq):
        with self._lock:
            self._pending.pop((op, seq), None)

    def pending_collectives(self):
        """Launched-but-unretired collectives, oldest first."""
        with self._lock:
            entries = list(self._pending.values())
        return sorted(entries, key=lambda e: e["launch_wall_ms"])

    def set_liveness_probe(self, probe):
        """Register a zero-arg callable naming the absent ranks (the
        kvstore wires ``dead_nodes`` here at ``create('dist_*')``)."""
        self._probe = probe

    # -- the postmortem artifact ---------------------------------------
    def snapshot(self, reason=None):
        from . import events
        now = time.time()
        recs = []
        for ts, kind, step, fields in list(self._ring):
            rec = {"kind": kind, "step": step,
                   "wall_ms": int(ts * 1000.0)}
            if fields:
                rec.update(fields)
            recs.append(rec)
        pend = self.pending_collectives()
        doc = {"reason": reason, "rank": events.rank(),
               "run_id": events.run_id(),
               "wall_ms": int(now * 1000.0), "depth": self.depth,
               "collective_seq": _trace.seq_snapshot(),
               "pending_collectives": [
                   dict(e, age_ms=int(now * 1000.0) - e["launch_wall_ms"])
                   for e in pend],
               "events": recs}
        if self._probe is not None:
            try:
                doc["absent_ranks"] = sorted(self._probe())
            except Exception:
                doc["absent_ranks"] = None
        try:
            doc["threads"] = thread_stacks()
        except Exception:
            doc["threads"] = None
        return doc

    def dump(self, reason, directory=None, extra=None):
        """Write the snapshot to ``<dir>/flight-rank%05d-%d.json`` and
        return the path (None on failure — a dump must never turn a
        crash into a different crash)."""
        try:
            doc = self.snapshot(reason=reason)
            if extra:
                doc.update(extra)
            directory = directory or dump_dir()
            os.makedirs(directory, exist_ok=True)
            path = os.path.join(directory, "flight-rank%05d-%d.json"
                                % (doc["rank"], self.dumps))
            self.dumps += 1
            with open(path + ".tmp", "w") as fout:
                json.dump(doc, fout, default=str, indent=1)
            os.replace(path + ".tmp", path)
            print("FLIGHT RECORDER: dumped %d events, %d pending "
                  "collective(s) to %s (reason: %s)"
                  % (len(doc["events"]),
                     len(doc["pending_collectives"]), path, reason),
                  file=sys.stderr, flush=True)
            return path
        except Exception:
            return None


# ----------------------------------------------------------------------
# process singleton
# ----------------------------------------------------------------------
_STATE = {"rec": None, "depth": None}
_SIG = {"installed": False}


def get():
    """The process FlightRecorder, or None when ``MXTPU_FLIGHT_DEPTH=0``.
    The depth env is read once at first use (:func:`reset` re-reads)."""
    if _STATE["depth"] is None:
        _STATE["depth"] = depth()
        if _STATE["depth"] > 0:
            _STATE["rec"] = FlightRecorder(_STATE["depth"])
    if _STATE["rec"] is not None:
        _install_sigterm()      # no-op once installed; retries when the
    return _STATE["rec"]        # first get() ran off the main thread


def reset():
    """Drop the singleton and re-read ``MXTPU_FLIGHT_DEPTH`` (tests)."""
    _STATE["rec"] = None
    _STATE["depth"] = None
    return get()


def note(kind, step, fields):
    """Ring-append one event (the :func:`events.emit` hook — called on
    every emit whether or not telemetry is enabled)."""
    rec = _STATE["rec"]
    if rec is None:
        if _STATE["depth"] is None:
            rec = get()
        if rec is None:
            return
    rec.note(kind, step, fields)


def collective_begin(op, seq, participants=None, **fields):
    rec = get()
    if rec is not None:
        rec.collective_begin(op, seq, participants=participants, **fields)


def collective_end(op, seq):
    rec = _STATE["rec"]
    if rec is not None:
        rec.collective_end(op, seq)


def pending_collectives():
    rec = _STATE["rec"]
    return rec.pending_collectives() if rec is not None else []


def set_liveness_probe(probe):
    rec = get()
    if rec is not None:
        rec.set_liveness_probe(probe)


def dump(reason, directory=None, extra=None):
    """Dump the singleton's snapshot (None when disabled/failed)."""
    rec = get()
    if rec is None:
        return None
    return rec.dump(reason, directory=directory, extra=extra)


def _install_sigterm():
    """Chain a SIGTERM handler that dumps before the previous behavior
    runs (the serving drain handler, the default kill).  Main-thread
    only (signal API constraint); a later main-thread get() retries."""
    if _SIG["installed"]:
        return
    if threading.current_thread() is not threading.main_thread():
        return
    try:
        prev = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            dump("sigterm")
            if callable(prev):
                prev(signum, frame)
            else:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signum)

        signal.signal(signal.SIGTERM, _on_term)
        _SIG["installed"] = True
    except (ValueError, OSError):       # non-main thread / exotic host
        pass
