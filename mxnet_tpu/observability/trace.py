"""Dapper-style trace/span ids over the existing telemetry events.

``MXTPU_TRACE=1`` threads three id fields through every span record the
tree already emits (serving request -> queue -> pack -> device ->
unpack; training ``data_wait``/``h2d``/``step``/``allreduce`` per
rank):

- ``trace_id`` — one id per logical unit of work (a training thread's
  run, a serving request),
- ``span_id`` — unique per span,
- ``parent_span`` — the enclosing span on the same thread, so nesting
  (``allreduce`` inside ``step``) reconstructs without timestamps.

Ids are maintained on a per-thread stack: :func:`begin_span` pushes,
:func:`end_span` pops, :func:`ids` reads the current frame for emits
that happen *inside* a span (the kvstore's ``collective`` record binds
to the enclosing ``allreduce`` span this way).

Cross-RANK stitching deliberately does not use trace ids (no rank ever
sees a peer's ids): each collective launch is tagged with a
**per-op sequence number** from :func:`next_seq`.  Launch order is
rank-uniform by construction (``@collective_seam`` — bucket layout and
the single-FIFO launcher make every rank run the same collectives in
the same order), so ``(op, seq)`` names the same physical collective
on every rank.  ``tools/mxtrace.py`` turns matching ``(op, seq)``
pairs into Chrome-trace flow arrows; ``flight.py`` keys its
pending-collective ledger on them.

Overhead: :func:`enabled` is one cached env probe (same rate-limited
pattern as :mod:`.events`); everything else is a couple of dict ops on
a ``threading.local``.
"""
from __future__ import annotations

import os
import threading
import time

__all__ = ["enabled", "refresh", "new_id", "begin_span", "end_span",
           "ids", "current_trace", "set_trace", "clear_trace",
           "next_seq", "seq_snapshot"]

_TRUE = ("1", "true", "on", "yes")

# rate-limited env probe (mirrors events._STATE: the per-span fast
# path must not hit os.environ every call)
_STATE = {"on": False, "checked": -1.0}
_RECHECK_S = 1.0


def enabled():
    """Tracing on?  (``MXTPU_TRACE`` truthy; cached ~1s like the event
    log's env probe — tests flipping the env call :func:`refresh`.)"""
    now = time.monotonic()
    if 0.0 <= now - _STATE["checked"] < _RECHECK_S:
        return _STATE["on"]
    _STATE["checked"] = now
    raw = os.environ.get("MXTPU_TRACE")
    _STATE["on"] = raw is not None and raw.strip().lower() in _TRUE
    return _STATE["on"]


def refresh():
    """Re-probe ``MXTPU_TRACE`` immediately."""
    _STATE["checked"] = -1.0
    return enabled()


# ----------------------------------------------------------------------
# id generation + per-thread span stack
# ----------------------------------------------------------------------
_local = threading.local()
_COUNT_LOCK = threading.Lock()
_COUNTER = [0]


def new_id():
    """A fresh 64-bit hex id: wall-clock + pid + a process counter —
    unique across the pod without coordination (ranks differ by pid
    and clock; threads by the counter)."""
    with _COUNT_LOCK:
        _COUNTER[0] += 1
        n = _COUNTER[0]
    return "%016x" % (((int(time.time() * 1e6) & 0xFFFFFFFF) << 32)
                      ^ (os.getpid() << 16) ^ n)


def _stack():
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


def current_trace():
    """This thread's trace id, creating one on first use (a training
    thread is one trace unless :func:`set_trace` scoped it)."""
    tid = getattr(_local, "trace_id", None)
    if tid is None:
        tid = _local.trace_id = new_id()
    return tid


def set_trace(trace_id):
    """Adopt ``trace_id`` as this thread's current trace (serving: the
    batch adopts the head request's trace).  Returns the previous id
    (or None) so the caller can restore it."""
    prev = getattr(_local, "trace_id", None)
    _local.trace_id = trace_id
    return prev


def clear_trace(prev=None):
    """Restore the thread's trace id (pair with :func:`set_trace`)."""
    _local.trace_id = prev


def begin_span(name):
    """Push a span frame; returns its id fields (the dict the span
    record will carry).  No-op returning ``{}`` when tracing is off."""
    if not enabled():
        return {}
    st = _stack()
    frame = {"trace_id": current_trace(), "span_id": new_id()}
    if st:
        frame["parent_span"] = st[-1]["span_id"]
    st.append(frame)
    return dict(frame)


def end_span():
    """Pop the innermost span frame (never raises on imbalance)."""
    st = _stack()
    if st:
        st.pop()


def ids():
    """Id fields binding an emit to the ENCLOSING span on this thread
    (``{}`` when tracing is off or no span is open).  The kvstore's
    ``collective`` record uses this to live inside its ``allreduce``
    span in the merged trace."""
    if not enabled():
        return {}
    st = _stack()
    if not st:
        return {"trace_id": current_trace()}
    top = st[-1]
    return {"trace_id": top["trace_id"], "span_id": top["span_id"]}


# ----------------------------------------------------------------------
# rank-uniform collective sequence numbers
# ----------------------------------------------------------------------
_SEQ_LOCK = threading.Lock()
_SEQ = {}


def next_seq(op):
    """The next sequence number for collective kind ``op`` (0-based,
    process-global, always on — the flight recorder needs it with
    telemetry off).  Rank-uniform because every rank launches the same
    collectives in the same order (``@collective_seam`` invariant), so
    ``(op, seq)`` identifies ONE pod-wide collective."""
    with _SEQ_LOCK:
        n = _SEQ.get(op, 0)
        _SEQ[op] = n + 1
        return n


def seq_snapshot():
    """{op: count issued so far} — flight dumps include it so "rank 3
    is one allreduce behind" is readable straight off two dumps."""
    with _SEQ_LOCK:
        return dict(_SEQ)
