"""Live SLO engine: burn-rate alerting over the metrics registry.

:mod:`.slo` prices *committed* BENCH files after the fact; nothing in
the tree evaluated an objective **live**.  This module closes that gap:
it reads the windowed sketches in the :mod:`.metrics` registry,
computes multi-window burn rates, raises/clears tiered alerts with
hysteresis, and writes advisory scale recommendations to the
coordination KV — the structured signal the (future) autoscaling
controller will consume.  Today mxtop observes them; nobody acts.

**Spec grammar** — ``MXTPU_SLO_SPEC`` is either inline spec(s) or a
path to a file of one spec per line (``#`` comments).  A spec is
colon-separated ``key=value`` pairs, specs separated by ``;``::

    metric=mxtpu_serve_latency_ms:target=250:budget=0.01

- ``metric``    histogram name in the registry (required)
- ``target``    objective threshold in metric units (required) —
  "a good event is a sample <= target"
- ``budget``    allowed bad-event fraction (default 0.01, i.e. 99%)
- ``page``      page-tier burn multiple (default 14)
- ``ticket``    ticket-tier burn multiple (default 2)
- ``fast``/``slow``          page window pair, seconds (default the
  two smallest configured windows: slow=60, fast=10)
- ``tfast``/``tslow``        ticket window pair (default the next
  pair up: tslow=300, tfast=60)
- ``clear``     hysteresis clear ratio (default 0.5): an active alert
  clears only after ``hold`` consecutive evaluations with every
  windowed burn below ``tier_threshold * clear``
- ``hold``      consecutive clear evaluations required (default 3)
- ``min_n``     minimum window sample count before a verdict (default
  10; thin windows neither fire nor clear — no verdicts from noise)

**Burn-rate math** (Google SRE Workbook multi-window multi-burn-rate):
``burn(w) = bad_fraction(w) / budget`` where ``bad_fraction`` counts
sketch samples above ``target`` in window ``w``.  A tier fires when
**both** its windows burn past its multiple — the long window proves
the spend is real, the short window proves it is *still happening*
(and makes recovery clear fast).  ``burn == 1`` means spending exactly
the budget; 14x over a 1%-budget objective pages because the error
budget would be gone within hours.

**Outputs**

- structured ``slo_alert`` events (fire and clear edges, flight-ring
  automatic like every emit),
- generation-stamped ``recommend_grow`` / ``recommend_shrink`` records
  under ``mxtpu_slo/`` in the coordination KV (schema:
  docs/observability.md "Live metrics & SLO engine") — advisory only,
- a JSON-able :meth:`SloEngine.state` snapshot mxtop renders.

Every clock the engine reads is injectable (``evaluate(now=...)``), so
the burn-rate matrix in tests is fully deterministic.
"""
from __future__ import annotations

import json
import os
import threading

from . import events
from . import metrics as _metrics

__all__ = ["SloSpec", "SloEngine", "parse_specs", "engine",
           "reset_engine", "maybe_start", "SLO_PREFIX"]

#: coordination-KV prefix for scale recommendations
SLO_PREFIX = "mxtpu_slo/"

_DEFAULTS = dict(budget=0.01, page=14.0, ticket=2.0, clear=0.5,
                 hold=3, min_n=10)


class SloSpec(object):
    """One parsed objective (see module docstring for the grammar)."""

    __slots__ = ("metric", "target", "budget", "page", "ticket",
                 "fast", "slow", "tfast", "tslow", "clear", "hold",
                 "min_n")

    def __init__(self, metric, target, budget=None, page=None,
                 ticket=None, fast=None, slow=None, tfast=None,
                 tslow=None, clear=None, hold=None, min_n=None):
        self.metric = str(metric)
        self.target = float(target)
        self.budget = float(_DEFAULTS["budget"] if budget is None
                            else budget)
        if not 0.0 < self.budget < 1.0:
            raise ValueError("budget must be in (0,1): %r" % budget)
        self.page = float(_DEFAULTS["page"] if page is None else page)
        self.ticket = float(_DEFAULTS["ticket"] if ticket is None
                            else ticket)
        wins = _metrics.windows()
        self.fast = int(fast) if fast is not None else wins[0]
        self.slow = int(slow) if slow is not None \
            else (wins[1] if len(wins) > 1 else wins[0] * 6)
        self.tfast = int(tfast) if tfast is not None else self.slow
        self.tslow = int(tslow) if tslow is not None \
            else (wins[2] if len(wins) > 2 else self.slow * 5)
        self.clear = float(_DEFAULTS["clear"] if clear is None
                           else clear)
        self.hold = int(_DEFAULTS["hold"] if hold is None else hold)
        self.min_n = int(_DEFAULTS["min_n"] if min_n is None
                         else min_n)

    def windows(self):
        return sorted({self.fast, self.slow, self.tfast, self.tslow})

    def to_dict(self):
        return {k: getattr(self, k) for k in self.__slots__}

    def __repr__(self):
        return "SloSpec(%s<=%g, budget=%g)" % (self.metric,
                                               self.target, self.budget)


def _parse_one(blob):
    kv = {}
    for part in blob.strip().split(":"):
        if not part:
            continue
        if "=" not in part:
            raise ValueError("bad SLO spec token %r in %r"
                             % (part, blob))
        key, _, val = part.partition("=")
        kv[key.strip()] = val.strip()
    if "metric" not in kv or "target" not in kv:
        raise ValueError("SLO spec needs metric= and target=: %r"
                         % blob)
    num = {k: float(v) for k, v in kv.items() if k != "metric"}
    return SloSpec(metric=kv["metric"], **num)


def parse_specs(raw=None):
    """``MXTPU_SLO_SPEC`` (or ``raw``) -> [SloSpec].  A value naming an
    existing file is read as one spec per line; inline values hold
    ``;``-separated specs.  Unset/empty -> []."""
    raw = raw if raw is not None else os.environ.get("MXTPU_SLO_SPEC")
    if not raw:
        return []
    raw = raw.strip()
    if os.path.isfile(raw):
        with open(raw) as fin:
            lines = [ln.strip() for ln in fin
                     if ln.strip() and not ln.strip().startswith("#")]
        return [_parse_one(ln) for ln in lines]
    return [_parse_one(blob) for blob in raw.split(";")
            if blob.strip()]


class _TierState(object):
    """Hysteresis ledger for one (spec, tier)."""

    __slots__ = ("active", "clear_streak", "fired_at", "last_burns")

    def __init__(self):
        self.active = False
        self.clear_streak = 0
        self.fired_at = None
        self.last_burns = {}


class SloEngine(object):
    """Continuous evaluator: call :meth:`evaluate` at poll cadence (or
    :meth:`start` a daemon thread that does).  All state transitions
    emit ``slo_alert`` events; page-tier fires write ``recommend_grow``
    and sustained idle writes ``recommend_shrink``.
    """

    #: burn level below which a window counts toward the idle streak
    IDLE_BURN = 0.1
    #: consecutive idle evaluations before a shrink recommendation
    IDLE_HOLD = 6

    def __init__(self, specs=None, reg=None, kv=None, source=None):
        self.specs = list(specs) if specs is not None else parse_specs()
        self._reg = reg
        self._kv = kv
        self.source = source or "sloengine"
        self._gen = 0
        self._tiers = {}         # (metric, tier) -> _TierState
        self._idle = {}          # metric -> consecutive idle evals
        self._last_alert = None
        self._last_reco = None
        self._evals = 0
        self._lock = threading.Lock()
        self._thread = None
        self._stop = threading.Event()

    # -- plumbing ------------------------------------------------------
    @property
    def registry(self):
        return self._reg or _metrics.registry()

    def _kv_client(self):
        if self._kv is not None:
            return self._kv
        from . import aggregate
        return aggregate._client()

    def _burn(self, spec, window_s, now):
        """(burn rate, sample count) over one window, or (None, n)
        when the window is too thin for a verdict."""
        hist = None
        for h in self.registry.histograms(spec.metric):
            hist = h
            break
        if hist is None:
            return None, 0
        sk = hist.window_sketch(window_s, now=now)
        if sk.count < spec.min_n:
            return None, sk.count
        bad = sk.count_above(spec.target) / float(sk.count)
        return bad / spec.budget, sk.count

    # -- evaluation ----------------------------------------------------
    def evaluate(self, now=None):
        """One evaluation pass over every spec.  Returns the list of
        alert event dicts emitted this pass (fires AND clears)."""
        import time as _t
        now = _t.time() if now is None else float(now)
        emitted = []
        with self._lock:
            self._evals += 1
            for spec in self.specs:
                emitted.extend(self._eval_spec(spec, now))
        return emitted

    def _eval_spec(self, spec, now):
        out = []
        burns = {}
        for w in spec.windows():
            burn, n = self._burn(spec, w, now)
            burns[w] = {"burn": burn, "n": n}
        for tier, mult, pair in (
                ("page", spec.page, (spec.slow, spec.fast)),
                ("ticket", spec.ticket, (spec.tslow, spec.tfast))):
            st = self._tiers.setdefault((spec.metric, tier),
                                        _TierState())
            pair_burns = [burns[w]["burn"] for w in pair]
            st.last_burns = {str(w): burns[w]["burn"] for w in pair}
            if any(b is None for b in pair_burns):
                continue         # thin window: no verdict either way
            breach = all(b >= mult for b in pair_burns)
            if breach and not st.active:
                st.active = True
                st.clear_streak = 0
                st.fired_at = now
                alert = self._emit_alert(
                    spec, tier, "fire", pair, pair_burns, mult, now)
                out.append(alert)
                if tier == "page":
                    self._recommend(spec, "recommend_grow", alert, now)
            elif st.active:
                cleared = all(b < mult * spec.clear
                              for b in pair_burns)
                if cleared:
                    st.clear_streak += 1
                    if st.clear_streak >= spec.hold:
                        st.active = False
                        st.clear_streak = 0
                        out.append(self._emit_alert(
                            spec, tier, "clear", pair, pair_burns,
                            mult, now))
                else:
                    st.clear_streak = 0
        # idle tracking: sustained near-zero burn on the slow window
        # with real traffic -> the fleet is oversized for the load
        slow = burns.get(spec.tslow) or burns.get(spec.slow) or {}
        page_active = self._tiers[(spec.metric, "page")].active
        ticket_active = self._tiers[(spec.metric, "ticket")].active
        if (slow.get("burn") is not None
                and slow["burn"] <= self.IDLE_BURN
                and not page_active and not ticket_active):
            self._idle[spec.metric] = self._idle.get(spec.metric, 0) + 1
            if self._idle[spec.metric] == self.IDLE_HOLD:
                self._recommend(spec, "recommend_shrink", {
                    "metric": spec.metric, "tier": "idle",
                    "burns": {str(spec.tslow): slow.get("burn")},
                }, now)
        else:
            self._idle[spec.metric] = 0
        return out

    # -- outputs -------------------------------------------------------
    def _emit_alert(self, spec, tier, edge, pair, pair_burns, mult,
                    now):
        alert = {"metric": spec.metric, "tier": tier, "edge": edge,
                 "target": spec.target, "budget": spec.budget,
                 "threshold_burn": mult,
                 "windows_s": list(pair),
                 "burns": {str(w): round(b, 3)
                           for w, b in zip(pair, pair_burns)},
                 "at": now, "source": self.source}
        self._last_alert = alert
        events.emit("slo_alert", **alert)
        events.flush()
        return alert

    def _recommend(self, spec, action, evidence, now):
        """Write one generation-stamped advisory scale record under
        ``mxtpu_slo/``.  KV unreachable -> skip silently (advice is
        droppable; the hold-the-verdict discipline belongs to readers,
        and fabricating staleness here would be worse than silence)."""
        self._gen += 1
        reason = ("page-tier burn %s over %ss/%ss windows"
                  % (evidence.get("burns"), spec.slow, spec.fast)
                  if action == "recommend_grow" else
                  "burn <= %g for %d evaluations"
                  % (self.IDLE_BURN, self.IDLE_HOLD))
        rec = {"action": action, "gen": self._gen,
               "metric": spec.metric, "target": spec.target,
               "budget": spec.budget, "reason": reason,
               "evidence": evidence, "at": now,
               "source": self.source}
        self._last_reco = rec
        events.emit("counter", name="slo_recommendation", **rec)
        try:
            client = self._kv_client()
            if client is not None:
                blob = json.dumps(rec, default=str, sort_keys=True,
                                  separators=(",", ":"))
                client.key_value_set(
                    "%sreco-%s-%05d" % (SLO_PREFIX, spec.metric,
                                        self._gen),
                    blob, allow_overwrite=True)
                client.key_value_set(SLO_PREFIX + "latest",
                                     blob, allow_overwrite=True)
        except Exception:
            pass
        return rec

    # -- views ---------------------------------------------------------
    def state(self, now=None):
        """JSON-able snapshot for mxtop's SLO pane: per-spec objective,
        current windowed burns, tier states, last alert/reco."""
        import time as _t
        now = _t.time() if now is None else float(now)
        specs = []
        with self._lock:
            for spec in self.specs:
                burns = {}
                for w in spec.windows():
                    burn, n = self._burn(spec, w, now)
                    burns[str(w)] = {
                        "burn": None if burn is None
                        else round(burn, 3), "n": n}
                tiers = {}
                for tier in ("page", "ticket"):
                    st = self._tiers.get((spec.metric, tier))
                    tiers[tier] = {
                        "active": bool(st and st.active),
                        "clear_streak": st.clear_streak if st else 0}
                specs.append({"metric": spec.metric,
                              "target": spec.target,
                              "budget": spec.budget,
                              "burns": burns, "tiers": tiers})
            return {"specs": specs, "evals": self._evals,
                    "last_alert": self._last_alert,
                    "last_recommendation": self._last_reco}

    # -- background loop ----------------------------------------------
    def start(self, interval_s=None):
        """Poll :meth:`evaluate` on a daemon thread (idempotent)."""
        if interval_s is None:
            try:
                interval_s = float(
                    os.environ.get("MXTPU_SLO_INTERVAL_S", "2"))
            except ValueError:
                interval_s = 2.0
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, args=(interval_s,), daemon=True,
                name="mxtpu-sloengine")
            self._thread.start()
        return self

    def _run(self, interval_s):
        while not self._stop.wait(interval_s):
            try:
                self.evaluate()
            except Exception:    # advisory tier: never kill the host
                pass

    def stop(self):
        self._stop.set()
        t = self._thread
        self._thread = None      # mxl: thread-shared-ok (MXL-Q001)
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)


_ENGINE = {"eng": None}


def engine():
    """The process SloEngine singleton (specs from the environment)."""
    if _ENGINE["eng"] is None:
        _ENGINE["eng"] = SloEngine()
    return _ENGINE["eng"]


def reset_engine():
    eng = _ENGINE["eng"]
    if eng is not None:
        eng.stop()
    _ENGINE["eng"] = None


def maybe_start(source=None, kv=None):
    """Server-door seam: when ``MXTPU_SLO_SPEC`` names objectives,
    start the background evaluator and return it; else None.  Called
    by mxserve/mxfleet at serve start."""
    specs = parse_specs()
    if not specs:
        return None
    eng = engine()
    if source:
        eng.source = source
    if kv is not None:
        eng._kv = kv
    eng.specs = specs
    return eng.start()
