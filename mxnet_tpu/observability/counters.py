"""Derived training counters: throughput, step-time EMA + percentiles,
MFU, HBM bytes, collective bytes, loss-scale/sentinel state.

One :class:`StepStats` per process folds host step timings into the
numbers an operator actually reads (EMA, p50/p95, samples/sec); the
helpers below pull the heavier figures from machinery that already
exists — XLA cost analysis via
``ShardedTrainer.compiled_step_cost_analysis()`` (the hook bench.py
uses for its MFU figure), the analysis ICI cost model
(``analysis.propagation.comm_report``) for collective bytes, and
``ShardedTrainer.sentinel_stats()`` for loss-scale/skip counts.
"""
from __future__ import annotations

import os

from . import events
from .metrics import QuantileSketch, registry as _metrics_registry

__all__ = ["percentile", "rel_spread", "StepStats", "global_stats",
           "reset", "peak_tflops", "mfu", "collective_bytes",
           "emit_trainer_counters", "emit_sentinel_counters",
           "emit_static_roofline"]


def percentile(values, pct):
    """Nearest-rank percentile of a sequence (no numpy on the hot
    path); None for an empty input."""
    vals = sorted(values)
    if not vals:
        return None
    if len(vals) == 1:
        return vals[0]
    idx = max(0, min(len(vals) - 1,
                     int(round(pct / 100.0 * (len(vals) - 1)))))
    return vals[idx]


def rel_spread(values):
    """Robust relative spread of a metric series: median absolute
    deviation over |median| (0.0 for <2 samples or a zero median).
    The noise estimate the SLO sentry (:mod:`.slo`) widens its
    regression thresholds by — MAD, not stddev, because a bench
    trajectory routinely contains one wild outlier round."""
    vals = [float(v) for v in values if v is not None]
    if len(vals) < 2:
        return 0.0
    med = percentile(vals, 50)
    if not med:
        return 0.0
    mad = percentile([abs(v - med) for v in vals], 50)
    return abs(mad / med)


class StepStats(object):
    """Step-time EMA + sketch-backed percentiles + throughput.

    ``observe`` is the hot call: one sketch bucket increment + one
    multiply-add (previously a deque append whose ``snapshot`` sorted a
    512-sample window — O(n·log n) per snapshot and unmergeable across
    ranks).  The :class:`~.metrics.QuantileSketch` backing p50/p95 is
    bounded-memory, bit-exactly mergeable, and rides along in the
    snapshot (``step_sketch``) so pod aggregation merges rank
    distributions instead of averaging per-rank percentiles.  A tighter
    ``alpha`` than the registry default keeps the snapshot numbers
    within 0.5% of the raw-sample truth.  The process singleton also
    mirrors timings into the live metrics registry (``mxtpu_step_ms``)
    so training shares the /metrics + SLO path with serving.
    """

    SKETCH_ALPHA = 0.005

    def __init__(self, batch_size=None, window=512, ema_decay=0.9,
                 feed_registry=False):
        self.batch_size = batch_size
        del window                   # kept in the signature for compat
        self.sketch = QuantileSketch(alpha=self.SKETCH_ALPHA)
        self.ema_decay = float(ema_decay)
        self.ema_s = None
        self.steps = 0
        self.last_step = None
        self._feed_registry = bool(feed_registry)

    def observe(self, dur_s, step=None, batch_size=None):
        dur_s = float(dur_s)
        self.sketch.add(dur_s * 1e3)
        self.ema_s = dur_s if self.ema_s is None else (
            self.ema_decay * self.ema_s + (1.0 - self.ema_decay) * dur_s)
        self.steps += 1
        if step is not None:
            self.last_step = step
        if batch_size is not None:
            self.batch_size = batch_size
        if self._feed_registry:
            try:
                _metrics_registry().histogram(
                    "mxtpu_step_ms",
                    help="training step wall time (ms)",
                ).observe(dur_s * 1e3)
            except Exception:
                pass

    def snapshot(self):
        """Dict of derived figures (the compact per-rank summary the
        aggregator publishes).  Same public fields as ever; p50/p95 now
        come from the sketch, and ``step_sketch`` carries the full
        serialized distribution for exact cross-rank merging."""
        out = {"steps": self.steps, "last_step": self.last_step}
        if self.ema_s is not None:
            out["step_ms_ema"] = round(self.ema_s * 1e3, 3)
        if self.sketch.count:
            out["step_ms_p50"] = round(self.sketch.percentile(50), 3)
            out["step_ms_p95"] = round(self.sketch.percentile(95), 3)
            mean = self.sketch.mean()
            out["step_ms_mean"] = round(mean, 3)
            if self.batch_size and mean > 0:
                out["samples_per_sec"] = round(
                    self.batch_size / (mean / 1e3), 2)
            out["step_sketch"] = self.sketch.to_dict()
        return out


_GLOBAL = {"stats": None}


def global_stats():
    """The process-wide StepStats the built-in wiring feeds (the
    singleton also mirrors into the live metrics registry)."""
    if _GLOBAL["stats"] is None:
        _GLOBAL["stats"] = StepStats(feed_registry=True)
    return _GLOBAL["stats"]


def reset():
    _GLOBAL["stats"] = None


# ----------------------------------------------------------------------
# hardware-derived figures
# ----------------------------------------------------------------------
def peak_tflops(device_kind=None):
    """Per-chip peak TFLOPs: ``BENCH_PEAK_TFLOPS`` override, else the
    bench.py spec-sheet table (shared so bench and telemetry can never
    disagree on a peak), else None."""
    raw = os.environ.get("BENCH_PEAK_TFLOPS")
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    if device_kind is None:
        try:
            import jax
            device_kind = getattr(jax.devices()[0], "device_kind", None)
        except Exception:
            return None
    try:
        import bench
        peak, _note = bench._lookup_peak_tflops(device_kind)
        return peak
    except Exception:
        return None


def mfu(flops_per_step, step_time_s, n_devices=1, device_kind=None):
    """Model-FLOPs utilization, or None when the peak is unknown."""
    peak = peak_tflops(device_kind)
    if not peak or not step_time_s:
        return None
    return float(flops_per_step) / float(step_time_s) / (
        peak * 1e12 * max(1, int(n_devices)))


def collective_bytes(symbol, mesh, shapes=None, **analyze_kwargs):
    """Per-device ICI bytes of one step of ``symbol`` under ``mesh``,
    from the analysis cost model (MXL-P transfer rules) — the figure
    the collective audit already computes at lint time, exposed as a
    telemetry counter.  Returns the ``comm_report`` dict or None."""
    try:
        from .. import analysis
        from ..analysis.propagation import comm_report
        ctx_out = []
        analysis.analyze(symbol, shapes=shapes, mesh=mesh,
                         _ctx_out=ctx_out, **analyze_kwargs)
        return comm_report(ctx_out[0])
    except Exception:
        return None


# ----------------------------------------------------------------------
# emit helpers (each one guarded: no-ops when telemetry is off)
# ----------------------------------------------------------------------
def emit_trainer_counters(trainer, step_time_s=None):
    """Emit MFU/flops/HBM-bytes counters for a ShardedTrainer from the
    compiled step's XLA cost analysis (needs one executed step).
    Returns the fields emitted (or {})."""
    if not events.enabled():
        return {}
    fields = {}
    try:
        cost = trainer.compiled_step_cost_analysis()
    except Exception:
        cost = None
    if cost:
        if cost.get("flops"):
            fields["flops_per_step"] = float(cost["flops"])
        if cost.get("bytes accessed"):
            fields["hbm_bytes_per_step"] = float(cost["bytes accessed"])
    if step_time_s and fields.get("flops_per_step"):
        try:
            import jax
            n_dev = len(jax.devices())
            kind = getattr(jax.devices()[0], "device_kind", None)
        except Exception:
            n_dev, kind = 1, None
        util = mfu(fields["flops_per_step"], step_time_s, n_dev, kind)
        if util is not None:
            fields["mfu"] = round(util, 4)
        fields["step_time_s"] = round(float(step_time_s), 6)
    stats = _GLOBAL["stats"]
    if stats is not None and stats.sketch.count:
        # the full step-time distribution rides along so pod rollups
        # merge rank sketches exactly instead of averaging percentiles
        fields["step_sketch"] = stats.sketch.to_dict()
    if fields:
        events.emit("counter", step=getattr(trainer, "num_update", None),
                    name="trainer_cost", **fields)
    return fields


def emit_static_roofline(symbol, shapes, device_kind=None,
                         compute_dtype=None):
    """Emit the analyzer's chip-free MXL-R roofline for ``symbol`` as a
    ``static_roofline`` counter (flops/bytes/intensity/MFU ceiling), so
    the measured-vs-ceiling gap is trackable in the event log next to
    ``trainer_cost``.  Returns the report dict (or {})."""
    if not events.enabled():
        return {}
    try:
        from ..analysis import static_mfu_ceiling
        rep = static_mfu_ceiling(symbol, shapes, device_kind=device_kind,
                                 compute_dtype=compute_dtype)
    except Exception:
        return {}
    events.emit("counter", name="static_roofline",
                flops_per_step=rep["flops_per_step"],
                hbm_bytes_per_step=rep["hbm_bytes_per_step"],
                intensity=rep["intensity"],
                mfu_ceiling=rep["mfu_ceiling"],
                bound=rep["bound"],
                device_kind=rep["device_kind"],
                compute_dtype=rep["compute_dtype"])
    return rep


def emit_sentinel_counters(stats, step=None):
    """Emit loss-scale / skip-count counters from a sentinel-stats dict
    (``ShardedTrainer.sentinel_stats()`` or a host ``Sentinel``)."""
    if not events.enabled() or not stats:
        return
    events.emit("counter", step=step, name="sentinel",
                loss_scale=stats.get("scale"),
                skipped=stats.get("skipped"),
                good_steps=stats.get("good_steps"),
                last_good=stats.get("last_good"))
