"""Pod-wide aggregation: per-rank summaries -> one coherent pod view.

Two data paths, one report shape:

- **Live** (a running pod): every rank publishes its compact per-step
  summary through the jax coordination-service KV — the SAME channel
  the PR-3 heartbeats and barrier/collective verdicts ride, so there is
  no second RPC fabric to configure or fail independently.  The
  coordinator merges them with :func:`pod_view`.  Heartbeat ages come
  from the existing ``mxtpu_hb/<rank>`` liveness stamps
  (:func:`heartbeat_ages` — the `kvstore.num_dead_nodes` data, exposed
  as ages instead of a dead count; deliberately NOT a second heartbeat).
- **Post-hoc** (a telemetry dir, live-tailed or after the job):
  :func:`read_events` merges the per-rank JSONL files and
  :func:`build_report` derives the same pod view from the records —
  what ``tools/mxtop.py`` renders.

Report fields (docs/observability.md): step-time p50/p95 pod-wide,
samples/sec (summed over ranks), MFU, straggler gap (max − median of
per-rank mean step time), per-rank heartbeat age, last fault per rank,
slowest phase, and the ordered fault/ckpt incident timeline.
"""
from __future__ import annotations

import glob as _glob
import json
import os
import time

from . import events, counters

__all__ = ["publish_summary", "collect_summaries", "heartbeat_ages",
           "pod_view", "read_events", "build_report", "timeline_around",
           "TEL_PREFIX"]

#: coordination-KV prefix for published per-rank summaries
TEL_PREFIX = "mxtpu_tel/"


def _client():
    from ..kvstore import _dist_client
    return _dist_client()


# KV fault discipline (docs/resilience.md): a coordination blip while
# reading summaries/heartbeat ages HOLDS the last good view instead of
# fabricating an empty pod — the same hold-the-verdict rule
# kvstore.dead_nodes applies — and telemeters the outage edge once
_HOLD = {"summaries": {}, "hb_ages": {}, "down": False}


def _kv_held(name, exc):
    """One read failed: note the outage once, serve the held copy."""
    if not _HOLD["down"]:
        _HOLD["down"] = True
        try:
            import mxnet_tpu.observability as _obs
            _obs.emit("fault", fault="kv_unreachable",
                      scope="telemetry_aggregate", op=name,
                      error=repr(exc))
        except Exception:
            pass
    return dict(_HOLD[name])


def _kv_good(name, value):
    """A read answered: refresh the held copy, close the outage."""
    _HOLD[name] = dict(value)
    if _HOLD["down"]:
        _HOLD["down"] = False
        try:
            import mxnet_tpu.observability as _obs
            _obs.emit("fault", fault="kv_recovered",
                      scope="telemetry_aggregate", op=name)
        except Exception:
            pass
    return value


# ----------------------------------------------------------------------
# live path (coordination-service KV)
# ----------------------------------------------------------------------
def publish_summary(step=None, extra=None):
    """Publish this rank's compact summary under ``mxtpu_tel/<rank>``
    (overwrite-in-place: one key per rank, O(ranks) total KV state).
    No-op without telemetry or a coordination client; never raises."""
    if not events.enabled():
        return False
    client = _client()
    if client is None:
        return False
    summary = counters.global_stats().snapshot()
    summary["rank"] = events.rank()
    summary["run_id"] = events.run_id()
    summary["published_at"] = time.time()
    if step is not None:
        summary["last_step"] = step
    fault = events.last_fault()
    if fault is not None:
        summary["last_fault"] = fault
    if extra:
        summary.update(extra)
    try:
        client.key_value_set(TEL_PREFIX + str(events.rank()),
                             json.dumps(summary, default=str),
                             allow_overwrite=True)
        return True
    except Exception:
        return False


def collect_summaries():
    """All published rank summaries: {rank: summary dict}.  Empty when
    no coordination service is up (single process)."""
    client = _client()
    if client is None:
        return {}
    try:
        entries = dict(client.key_value_dir_get(TEL_PREFIX))
    except Exception as exc:  # unreachable KV: hold the last view
        return _kv_held("summaries", exc)
    out = {}
    for key, val in entries.items():
        try:
            rank = int(key[len(TEL_PREFIX):]) if key.startswith(TEL_PREFIX) \
                else int(key.rsplit("/", 1)[-1])
            out[rank] = json.loads(val)
        except (ValueError, TypeError):
            continue
    return _kv_good("summaries", out)


def heartbeat_ages(num_workers=None, now=None):
    """{rank: seconds since last liveness stamp} from the EXISTING
    kvstore heartbeat keys (``mxtpu_hb/<rank>``) — the same stamps
    ``num_dead_nodes`` thresholds, surfaced as ages so an operator sees
    "rank 3 last breathed 47s ago" before the dead-count trips.
    Ranks with no stamp yet map to None."""
    from ..kvstore import _HB_PREFIX, _now
    client = _client()
    if client is None:
        return {}
    try:
        entries = dict(client.key_value_dir_get(_HB_PREFIX))
    except Exception as exc:  # unreachable KV: hold the last ages —
        ages = _kv_held("hb_ages", exc)   # never "everyone silent"
    else:
        now = _now() if now is None else now
        ages = {}
        for key, stamp in entries.items():
            try:
                rank = int(key[len(_HB_PREFIX):]) \
                    if key.startswith(_HB_PREFIX) \
                    else int(key.rsplit("/", 1)[-1])
                ages[rank] = round(now - float(stamp), 3)
            except (ValueError, TypeError):
                continue
        _kv_good("hb_ages", ages)
    if num_workers:
        for rank in range(int(num_workers)):
            ages.setdefault(rank, None)
    return ages


def pod_view(num_workers=None):
    """Merge the live published summaries + heartbeat ages into the pod
    report (coordinator-side; any rank may call it)."""
    summaries = collect_summaries()
    ages = heartbeat_ages(num_workers)
    per_rank = {str(r): s for r, s in sorted(summaries.items())}
    for rank, age in ages.items():
        per_rank.setdefault(str(rank), {})["heartbeat_age_s"] = age
    pod = _pod_rollup(per_rank)
    return {"per_rank": per_rank, "pod": pod,
            "ranks": sorted(int(r) for r in per_rank)}


def _median(vals):
    vals = sorted(vals)
    if not vals:
        return None
    mid = len(vals) // 2
    if len(vals) % 2:
        return vals[mid]
    return 0.5 * (vals[mid - 1] + vals[mid])


def _pod_merged_sketch(per_rank):
    """Exact pod step-time distribution: the merge of every rank's
    published ``step_sketch`` — bit-identical to one sketch fed all
    ranks' streams.  None when no rank published one (old-format
    summaries), which keeps the legacy median/max math as fallback."""
    from .metrics import QuantileSketch
    sketches = [QuantileSketch.from_dict(s.get("step_sketch"))
                for s in per_rank.values() if s.get("step_sketch")]
    sketches = [sk for sk in sketches if sk is not None and sk.count]
    return QuantileSketch.merged(sketches) if sketches else None


def _pod_rollup(per_rank):
    """Pod-level figures from per-rank summary dicts (shared by the
    live and post-hoc paths).  When ranks publish step sketches the
    pod p50/p95 are EXACT (merged distribution); otherwise the legacy
    approximation (median of rank p50s / max of rank p95s) applies."""
    means = [s["step_ms_mean"] for s in per_rank.values()
             if s.get("step_ms_mean") is not None]
    merged = _pod_merged_sketch(per_rank)
    pod = {
        "ranks": len(per_rank),
        "steps": max([s.get("last_step") or 0
                      for s in per_rank.values()] or [0]),
        "step_ms_p50": round(merged.percentile(50), 3) if merged
        else _median([s.get("step_ms_p50") for s in
                      per_rank.values()
                      if s.get("step_ms_p50") is not None]),
        "step_ms_p95": round(merged.percentile(95), 3) if merged
        else max([s.get("step_ms_p95") for s in
                  per_rank.values()
                  if s.get("step_ms_p95") is not None] or
                 [None], key=lambda v: v or 0),
        "samples_per_sec": round(sum(
            s.get("samples_per_sec") or 0 for s in per_rank.values()), 2)
        or None,
        "mfu": None,
        "straggler_gap_ms": None,
        "slowest_phase": None,
        "heartbeat_age_s": {r: s.get("heartbeat_age_s")
                            for r, s in per_rank.items()},
    }
    if means:
        pod["straggler_gap_ms"] = round(max(means) - _median(means), 3)
    mfus = [s.get("mfu") for s in per_rank.values()
            if s.get("mfu") is not None]
    if mfus:
        pod["mfu"] = round(sum(mfus) / len(mfus), 4)
    return pod


# ----------------------------------------------------------------------
# post-hoc path (telemetry dir -> merged records -> report)
# ----------------------------------------------------------------------
def read_events(directory):
    """Merge every ``events-rank*.jsonl`` (rotated ``.1`` predecessors
    included) under ``directory`` into one wall-clock-ordered list of
    record dicts.  Unparseable lines (torn final write of a killed
    rank) are skipped, not fatal."""
    paths = sorted(_glob.glob(os.path.join(directory,
                                           "events-rank*.jsonl.1")))
    paths += sorted(_glob.glob(os.path.join(directory,
                                            "events-rank*.jsonl")))
    records = []
    for path in paths:
        try:
            with open(path) as fin:
                for line in fin:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict):
                        records.append(rec)
        except OSError:
            continue
    records.sort(key=lambda r: (r.get("wall_ms") or 0,
                                r.get("rank") or 0))
    return records


class EventTailer(object):
    """Rotation-safe incremental reader over one telemetry dir.

    Each :meth:`poll` returns only the records appended since the last
    call, across every ``events-rank*.jsonl`` (and rotated ``.1``)
    file.  Offsets are tracked **per inode**, not per path: when the
    writer hits ``MXTPU_TELEMETRY_MAX_MB`` and renames the live file to
    ``.1``, the next poll drains the renamed file from its prior offset
    and starts the fresh live file at zero — a follower never tails a
    dead inode and never re-reads what it already returned.  A partial
    trailing line (a record mid-write) is carried per inode until a
    later poll completes it, so rotation/kill can tear at most the
    final unflushed record, never a returned one.
    """

    def __init__(self, directory):
        self.directory = str(directory)
        self._state = {}        # inode -> (byte offset, carry bytes)

    def poll(self):
        """New records (wall-clock ordered) since the previous poll."""
        paths = sorted(_glob.glob(os.path.join(
            self.directory, "events-rank*.jsonl.1")))
        paths += sorted(_glob.glob(os.path.join(
            self.directory, "events-rank*.jsonl")))
        records = []
        seen = set()
        for path in paths:
            try:
                with open(path, "rb") as fin:
                    ino = os.fstat(fin.fileno()).st_ino
                    seen.add(ino)
                    offset, carry = self._state.get(ino, (0, b""))
                    fin.seek(offset)
                    chunk = fin.read()
                    offset = fin.tell()
            except OSError:
                continue
            if not chunk:
                self._state[ino] = (offset, carry)
                continue
            lines = (carry + chunk).split(b"\n")
            carry = lines.pop()          # b"" when chunk ended on \n
            self._state[ino] = (offset, carry)
            for line in lines:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line.decode("utf-8", "replace"))
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
        for ino in list(self._state):    # bound: forget deleted files
            if ino not in seen:
                del self._state[ino]
        records.sort(key=lambda r: (r.get("wall_ms") or 0,
                                    r.get("rank") or 0))
        return records


def build_report(records, now=None):
    """The pod report from merged event records (what ``mxtop`` shows).

    Heartbeat ages: a live-published ``heartbeat_ages`` counter record
    (the drill/coordinator emits one from the KV liveness stamps) wins;
    otherwise each rank's age is derived from its LAST event — an
    honest "this rank last told us anything N seconds ago".
    """
    now_ms = (time.time() if now is None else now) * 1000.0
    ranks = sorted({r.get("rank") for r in records
                    if r.get("rank") is not None})
    run_ids = sorted({r.get("run_id") for r in records
                      if r.get("run_id")})
    per_rank = {}
    phase_totals = {}
    incidents = []
    kv_hb_ages = None
    last_elastic = None
    last_resume = None
    adopt_wall = {}             # generation -> newest propose/adopt wall
    for rec in records:
        kind = rec.get("kind")
        rank = rec.get("rank")
        state = per_rank.setdefault(str(rank), {
            "_durs": [], "_sps": [], "steps": 0, "last_step": None,
            "last_wall_ms": None, "last_fault": None})
        state["last_wall_ms"] = rec.get("wall_ms")
        if kind == "step":
            state["steps"] += 1
            if rec.get("step") is not None:
                state["last_step"] = rec["step"]
            if rec.get("dur_ms") is not None:
                state["_durs"].append(float(rec["dur_ms"]))
            if rec.get("samples_per_sec") is not None:
                state["_sps"].append(float(rec["samples_per_sec"]))
        elif kind == "span":
            name = rec.get("name") or "?"
            phase_totals[name] = phase_totals.get(name, 0.0) \
                + float(rec.get("dur_ms") or 0.0)
        elif kind == "fault":
            state["last_fault"] = {k: v for k, v in rec.items()
                                   if k not in ("run_id",)}
            incidents.append(rec)
        elif kind == "ckpt":
            incidents.append(rec)
        elif kind == "elastic":
            # re-mesh agreement trail: incident-worthy AND the pod's
            # generation/world_size come from the newest one (records
            # arrive wall-clock-sorted, so last seen wins)
            incidents.append(rec)
            if rec.get("generation") is not None:
                state["generation"] = rec.get("generation")
            last_elastic = rec
            event = rec.get("event")
            gen = rec.get("generation")
            if event in ("propose", "adopt") and gen is not None \
                    and rec.get("wall_ms") is not None:
                adopt_wall[gen] = max(adopt_wall.get(gen, 0),
                                      rec["wall_ms"])
            elif event == "resume":
                last_resume = rec
        elif kind == "counter" and rec.get("name") == "heartbeat_ages":
            kv_hb_ages = rec.get("ages")
        elif kind == "counter" and rec.get("name") == "trainer_cost":
            if rec.get("mfu") is not None:
                state.setdefault("_mfus", []).append(float(rec["mfu"]))
            if rec.get("step_sketch"):
                # the emitter's own cumulative sketch: newest wins (a
                # sketch is monotone, so the last one is the union)
                state["_pub_sketch"] = rec["step_sketch"]

    from .metrics import QuantileSketch
    summaries = {}
    for rank, state in per_rank.items():
        durs = state.pop("_durs")
        sps = state.pop("_sps")
        mfus = state.pop("_mfus", [])
        pub = state.pop("_pub_sketch", None)
        s = dict(state)
        # per-rank step-time distribution: the rank's own published
        # sketch when it emitted one, else the step records folded
        # into a fresh sketch — either way percentiles come from the
        # sketch, and the dict rides along so _pod_rollup merges
        # rank distributions exactly
        sketch = QuantileSketch.from_dict(pub) if pub else None
        if sketch is None and durs:
            sketch = QuantileSketch(
                alpha=counters.StepStats.SKETCH_ALPHA)
            sketch.extend(durs)
        if durs:
            s["step_ms_mean"] = round(sum(durs) / len(durs), 3)
        if sketch is not None and sketch.count:
            s["step_ms_p50"] = round(sketch.percentile(50), 3)
            s["step_ms_p95"] = round(sketch.percentile(95), 3)
            s.setdefault("step_ms_mean", round(sketch.mean(), 3))
            s["step_sketch"] = sketch.to_dict()
        if sps:
            s["samples_per_sec"] = round(sps[-1], 2)
        elif durs and s.get("step_ms_mean"):
            pass                        # no batch size known: omit
        if mfus:
            s["mfu"] = round(sum(mfus) / len(mfus), 4)
        if kv_hb_ages and str(rank) in {str(k) for k in kv_hb_ages}:
            age = kv_hb_ages.get(rank, kv_hb_ages.get(str(rank)))
            s["heartbeat_age_s"] = age
        elif state.get("last_wall_ms"):
            s["heartbeat_age_s"] = round(
                (now_ms - state["last_wall_ms"]) / 1e3, 3)
        summaries[rank] = s

    pod = _pod_rollup(summaries)
    if last_elastic is not None:
        pod["generation"] = last_elastic.get("generation")
        if last_elastic.get("world_size") is not None:
            pod["world_size"] = last_elastic.get("world_size")
        pod["last_elastic"] = {
            k: last_elastic.get(k)
            for k in ("event", "generation", "world_size", "reason",
                      "from_world", "rank", "step", "path",
                      "fallback_reason", "duration_ms")
            if last_elastic.get(k) is not None}
    if last_resume is not None:
        # the recovery-cost rollup (PR 11): which rung of the resume
        # ladder the last transition took (warm = host memory, cold =
        # checkpoint), the restore cost the resume event measured
        # itself, and the end-to-end transition wall — verdict
        # adopt/propose (old incarnation) to resume (new one), pairable
        # because both carry the agreed generation
        tr = {k: last_resume.get(k)
              for k in ("path", "generation", "step", "world_size",
                        "fallback_reason", "duration_ms")
              if last_resume.get(k) is not None}
        gen = last_resume.get("generation")
        if gen in adopt_wall and last_resume.get("wall_ms") is not None \
                and last_resume["wall_ms"] >= adopt_wall[gen]:
            tr["transition_ms"] = round(
                last_resume["wall_ms"] - adopt_wall[gen], 3)
        pod["last_transition"] = tr
    if phase_totals:
        pod["slowest_phase"] = max(phase_totals, key=phase_totals.get)
        pod["phase_totals_ms"] = {k: round(v, 3)
                                  for k, v in sorted(phase_totals.items())}
    # input-pipeline overlap proof (docs/perf.md "Overlap"): serial
    # phase time vs step wall — >1 means data_wait/h2d hid under compute
    from .spans import overlap_report
    ov = overlap_report(records)
    if ov["overlap_ratio"] is not None:
        pod["overlap_ratio"] = ov["overlap_ratio"]
        if ov["phase_p50_ms"]:
            pod["phase_p50_ms"] = ov["phase_p50_ms"]
    out = {"run_ids": run_ids, "ranks": ranks, "events": len(records),
           "pod": pod, "per_rank": summaries, "incidents": incidents}
    # serving rollup (docs/serving.md): per-model QPS/latency/occupancy
    # from "serve" records, when any exist (lazy import: serving is a
    # consumer of observability, not a dependency)
    try:
        from ..serving.telemetry import serve_report
        sv = serve_report(records)
    except Exception:
        sv = None
    if sv and sv.get("models"):
        out["serve"] = sv
    # fleet rollup (docs/serving.md "Fleet"): per-replica qps/p95/
    # occupancy/param-version + fleet-wide straggler gap and version
    # skew, from the replica-stamped serve records
    try:
        from ..serving.telemetry import fleet_report
        fl = fleet_report(records)
    except Exception:
        fl = None
    if fl and fl.get("replicas"):
        out["fleet"] = fl
    # retrace rollup (docs/perf.md, observability/retrace.py): the
    # sentry's attributed post-warmup lowerings — count, the divergent
    # cache-key ingredients, and the requesting sites.  Present only
    # when "retrace" records exist, i.e. the contract was violated.
    retraces = [r for r in records if r.get("kind") == "retrace"]
    if retraces:
        divergent = {}
        for r in retraces:
            for ingredient in (r.get("divergent") or ["unknown"]):
                divergent[ingredient] = divergent.get(ingredient, 0) \
                    + int(r.get("n") or 1)
        out["retrace"] = {
            "count": sum(int(r.get("n") or 1) for r in retraces),
            "divergent": dict(sorted(divergent.items())),
            "sites": sorted({r.get("site") for r in retraces
                             if r.get("site")})[:8],
        }
    # pipeline-schedule rollup (docs/graph_lint.md "MXL-E"): the
    # GPipe/1F1B shape + measured bubble fraction the GPipeTrainer
    # emits once on first build, and the expert load balance when an
    # MoE run reports one.  String-tolerant — these round-trip through
    # shell/env in the drills, so "0.33" reads like 0.33 and junk is
    # dropped rather than crashed on.
    scheds = [r for r in records if r.get("kind") == "schedule"]
    if scheds:
        def _flt(v):
            try:
                return float(v)
            except (TypeError, ValueError):
                return None
        last = scheds[-1]
        sched = {"schedule": str(last.get("schedule") or "?")}
        for key in ("stages", "microbatches"):
            n = _flt(last.get(key))
            if n is not None:
                sched[key] = int(n)
        for key in ("bubble_fraction", "expert_balance"):
            v = _flt(last.get(key))
            if v is not None:
                sched[key] = v
        out["schedule"] = sched
    # SLO rollup (observability/sloengine.py): alert edges and scale
    # recommendations, when the live engine emitted any — what the
    # mxtop SLO pane renders post-hoc
    alerts = [r for r in records if r.get("kind") == "slo_alert"]
    recos = [r for r in records if r.get("kind") == "counter"
             and r.get("name") == "slo_recommendation"]
    if alerts or recos:
        fires = [r for r in alerts if r.get("edge") == "fire"]
        active = {}
        for r in alerts:        # wall-clock order: last edge wins
            key = "%s/%s" % (r.get("metric"), r.get("tier"))
            active[key] = r.get("edge") == "fire"
        out["slo"] = {
            "alerts": len(fires),
            "page_alerts": len([r for r in fires
                                if r.get("tier") == "page"]),
            "active": sorted(k for k, v in active.items() if v),
            "last_alert": alerts[-1] if alerts else None,
            "recommendations": len(recos),
            "last_recommendation": recos[-1] if recos else None,
        }
    return out


def timeline_around(records, index, before=5, after=5):
    """The event window around ``records[index]`` (an incident) — what
    ``mxtop --fault`` prints so "what happened before the restart" is
    one command, not eight grepped logs."""
    lo = max(0, index - before)
    hi = min(len(records), index + after + 1)
    return records[lo:hi]
