"""Perf-regression sentry: counters vs the committed bench trajectory.

The repo commits one ``BENCH_*.json`` per PR round — a trajectory of
the numbers that must not silently regress (step time, images/sec,
allreduce time, transformer throughput) — and the telemetry stack
derives the live counterparts (step p50/p95, overlap_ratio, serving
padding waste, samples/sec).  Nothing compared them.  This module is
the comparison:

- :func:`load_bench` / :func:`load_trajectory` read the committed
  ``BENCH_*.json`` schema (``{"parsed": {...}, "rc": 0}``) into flat
  metric dicts; failed rounds (``rc != 0`` / null ``parsed``) are
  skipped, not fatal.
- :func:`telemetry_metrics` derives the same metric names from a
  merged telemetry report (``aggregate.build_report`` output), so a
  run's event dir can be diffed against a bench baseline directly.
- :func:`compare` applies **noise-aware thresholds**: a metric must
  move more than ``max(min_rel, sigma * rel_spread(trajectory))`` in
  its bad direction to count — a 10% floor keeps toy diffs quiet, the
  MAD-based spread keeps a historically-jittery metric (CPU-fallback
  images/sec swings round to round) from crying wolf.
- :func:`emit_regressions` records each finding as a structured
  ``perf_regression`` fault event, so regressions land in the mxtop
  incident timeline and the flight recorder like any other fault.

``tools/benchdiff.py`` is the CLI/CI gate over this module (nonzero
exit on any regression).  ``MXTPU_SLO_BASELINE`` names the default
baseline file or glob (default: ``BENCH_*.json`` in the repo root).
"""
from __future__ import annotations

import glob as _glob
import json
import os

from . import events
from .counters import rel_spread

__all__ = ["DIRECTIONS", "ZERO_ALERT", "baseline_spec", "load_bench",
           "load_trajectory", "telemetry_metrics", "trajectory_noise",
           "compare", "emit_regressions"]

#: metric -> which way is WORSE ("up" = a larger value is a
#: regression).  Only named metrics are compared; unknown keys in a
#: baseline are ignored rather than guessed at.
DIRECTIONS = {
    "step_time_ms": "up",
    "step_ms_p50": "up",
    "step_ms_p95": "up",
    "allreduce_time_ms": "up",
    "transformer_step_ms": "up",
    "serve_padding_waste": "up",
    "serve_ms_p95": "up",
    "serve_ttft_ms_p95": "up",
    "serve_itl_ms_p95": "up",
    "serve_tokens_per_sec": "down",
    "images_per_sec": "down",
    "module_path_images_per_sec": "down",
    "transformer_tokens_per_sec": "down",
    "samples_per_sec": "down",
    "serve_qps": "down",
    "overlap_ratio": "down",
    "mfu": "down",
    "allreduce_gbps": "down",
    # open-loop traffic realism (serve_bench --arrival / --tenant-mix):
    # achieved completion rate under the shaped offered load, and the
    # offered-minus-achieved deficit fraction (0 = the server kept up)
    "serve_achieved_rps": "down",
    "serve_rate_deficit": "up",
    # fleet serving (serve_bench --fleet / docs/serving.md "Fleet")
    "fleet_rps": "down",
    "fleet_balance_ratio": "up",
    "fleet_swap_pause_ms_p95": "up",
    "fleet_straggler_gap_ms": "up",
    # retrace sentry (observability/retrace.py): the steady-state
    # contract is exactly zero, so these sit in ZERO_ALERT too — any
    # nonzero value against a zero baseline flags regardless of the
    # relative-threshold math
    "retraces_after_warmup": "up",
    "lowerings_after_warmup": "up",
    "swap_lowerings": "up",
}

#: zero-contract metrics: the baseline is exactly 0 by design, so the
#: relative-delta machinery (undefined at base==0) is replaced by "any
#: nonzero current value is a regression"
ZERO_ALERT = ("retraces_after_warmup", "lowerings_after_warmup",
              "swap_lowerings")

#: default regression floor (relative) and noise multiplier
MIN_REL = 0.10
SIGMA = 3.0


def baseline_spec(default="BENCH_*.json"):
    """``MXTPU_SLO_BASELINE``: baseline file or glob for benchdiff and
    the sentry (a single file pins the baseline; a glob makes the
    newest file the baseline and the rest the noise trajectory)."""
    return os.environ.get("MXTPU_SLO_BASELINE") or default


def _bench_metrics(parsed):
    out = {}
    for key in ("step_time_ms", "allreduce_time_ms", "allreduce_gbps",
                "transformer_step_ms", "transformer_tokens_per_sec",
                "module_path_images_per_sec", "mfu",
                "retraces_after_warmup", "lowerings_after_warmup",
                "swap_lowerings"):
        if parsed.get(key) is not None:
            out[key] = float(parsed[key])
    if parsed.get("value") is not None \
            and parsed.get("unit") == "images/sec":
        out["images_per_sec"] = float(parsed["value"])
    if parsed.get("value") is not None \
            and parsed.get("metric") == "serve_tokens_per_sec":
        # serve_bench --generate BENCH line: throughput + the tail
        # latency pair the generation SLO story cares about
        out["serve_tokens_per_sec"] = float(parsed["value"])
        for src, dst in (("ttft_ms", "serve_ttft_ms_p95"),
                         ("itl_ms", "serve_itl_ms_p95")):
            p95 = (parsed.get(src) or {}).get("p95")
            if p95 is not None:
                out[dst] = float(p95)
    if parsed.get("achieved_rate") is not None:
        # serve_bench open-loop BENCH line (--arrival): achieved vs
        # offered rate — the traffic-realism pair benchdiff prices
        out["serve_achieved_rps"] = float(parsed["achieved_rate"])
        offered = parsed.get("offered_rate")
        if offered:
            out["serve_rate_deficit"] = round(max(
                0.0, (float(offered) - float(parsed["achieved_rate"]))
                / float(offered)), 4)
    if parsed.get("value") is not None \
            and parsed.get("metric") == "fleet_throughput_rps":
        # serve_bench --fleet BENCH line: fleet throughput plus the
        # two health numbers the fleet story gates on — dispatch
        # balance (1.0 = even) and the hot-swap rotation pause
        out["fleet_rps"] = float(parsed["value"])
        if parsed.get("balance_ratio") is not None:
            out["fleet_balance_ratio"] = float(parsed["balance_ratio"])
        if parsed.get("swap_pause_ms_p95") is not None:
            out["fleet_swap_pause_ms_p95"] = \
                float(parsed["swap_pause_ms_p95"])
    return out


def load_bench(path):
    """One committed ``BENCH_*.json`` -> flat metric dict, or None for
    a failed/unreadable round.  Also accepts a bare metric dict (a
    benchdiff ``--metrics`` snapshot) for synthetic comparisons."""
    try:
        with open(path) as fin:
            doc = json.load(fin)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict):
        return None
    parsed = doc.get("parsed")
    if parsed is not None:
        if doc.get("rc") not in (0, None):
            return None
        return _bench_metrics(parsed) or None
    if "rc" in doc or "cmd" in doc:
        return None                     # failed round: no parsed payload
    # bare metric dict: keep the keys the sentry knows
    out = {k: float(v) for k, v in doc.items()
           if k in DIRECTIONS and isinstance(v, (int, float))}
    return out or None


def load_trajectory(spec):
    """Expand a file-or-glob spec into ``[(path, metrics), ...]`` in
    name order (the repo's BENCH_r01..r0N naming is the time axis)."""
    paths = sorted(_glob.glob(spec)) if _glob.has_magic(spec) \
        else [spec]
    out = []
    for path in paths:
        metrics = load_bench(path)
        if metrics:
            out.append((path, metrics))
    return out


def telemetry_metrics(report):
    """The sentry's metric names from a merged telemetry report
    (``aggregate.build_report`` output) — so ``benchdiff --telemetry
    DIR`` prices a live run against the committed trajectory."""
    pod = report.get("pod") or {}
    out = {}
    for key in ("step_ms_p50", "step_ms_p95", "samples_per_sec",
                "overlap_ratio", "mfu"):
        if pod.get(key) is not None:
            out[key] = float(pod[key])
    total = (report.get("serve") or {}).get("total") or {}
    if total.get("padding_waste") is not None:
        out["serve_padding_waste"] = float(total["padding_waste"])
    if total.get("qps") is not None:
        out["serve_qps"] = float(total["qps"])
    lat = total.get("latency_ms") or {}
    if lat.get("p95") is not None:
        out["serve_ms_p95"] = float(lat["p95"])
    if total.get("tokens_per_sec") is not None:
        out["serve_tokens_per_sec"] = float(total["tokens_per_sec"])
    for src, dst in (("ttft_ms", "serve_ttft_ms_p95"),
                     ("itl_ms", "serve_itl_ms_p95")):
        p95 = (total.get(src) or {}).get("p95")
        if p95 is not None:
            out[dst] = float(p95)
    fleet = report.get("fleet") or {}
    if fleet.get("straggler_gap_ms") is not None:
        out["fleet_straggler_gap_ms"] = \
            float(fleet["straggler_gap_ms"])
    if fleet.get("balance_ratio") is not None:
        out["fleet_balance_ratio"] = float(fleet["balance_ratio"])
    retrace = report.get("retrace") or {}
    if retrace.get("count") is not None:
        out["retraces_after_warmup"] = float(retrace["count"])
    return out


def trajectory_noise(trajectory):
    """{metric: rel_spread over the trajectory} — the per-metric noise
    floor.  ``trajectory`` is ``load_trajectory`` output."""
    series = {}
    for _path, metrics in trajectory:
        for key, val in metrics.items():
            series.setdefault(key, []).append(val)
    return {key: rel_spread(vals) for key, vals in series.items()}


def compare(current, baseline, noise=None, min_rel=MIN_REL,
            sigma=SIGMA):
    """Diff ``current`` against ``baseline`` (flat metric dicts).

    Returns ``(regressions, checked)``: ``checked`` is every metric
    present in both with a known direction (each a dict with
    ``metric/current/baseline/delta_pct/threshold_pct/regression``);
    ``regressions`` is the subset that moved past its threshold in the
    bad direction.  Improvements never flag, whatever their size.
    """
    noise = noise or {}
    checked, regressions = [], []
    for metric in sorted(set(current) & set(baseline)):
        direction = DIRECTIONS.get(metric)
        if direction is None:
            continue
        base, cur = float(baseline[metric]), float(current[metric])
        if base == 0.0:
            if metric in ZERO_ALERT and cur > 0.0 and direction == "up":
                # zero-contract metric: no relative threshold exists —
                # the contract IS the zero, so any count regresses
                finding = {"metric": metric, "current": cur,
                           "baseline": base, "delta_pct": None,
                           "threshold_pct": 0.0,
                           "direction": direction, "regression": True}
                checked.append(finding)
                regressions.append(finding)
            continue
        thr = max(float(min_rel), float(sigma) * noise.get(metric, 0.0))
        delta = (cur - base) / abs(base)
        bad = delta if direction == "up" else -delta
        finding = {"metric": metric, "current": cur, "baseline": base,
                   "delta_pct": round(delta * 100.0, 2),
                   "threshold_pct": round(thr * 100.0, 2),
                   "direction": direction, "regression": bad > thr}
        checked.append(finding)
        if finding["regression"]:
            regressions.append(finding)
    return regressions, checked


def emit_regressions(regressions, step=None, baseline_name=None):
    """One structured ``perf_regression`` fault event per finding —
    the incident timeline / flight ring representation of "this build
    got slower".  Safe no-op list for empty input."""
    for f in regressions:
        events.emit("fault", step=step, fault="perf_regression",
                    phase="slo", metric=f["metric"],
                    current=f["current"], baseline=f["baseline"],
                    delta_pct=f["delta_pct"],
                    threshold_pct=f["threshold_pct"],
                    baseline_name=baseline_name)
    if regressions:
        events.flush()
    return regressions
